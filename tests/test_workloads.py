"""Workload library self-tests: each checker validated against a correct
in-memory backend (must pass) and a deliberately broken one (must fail),
run through the full core.run lifecycle."""

import pytest

from jepsen_tpu import core
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import compose, total_queue
from jepsen_tpu.history import History, Op
from jepsen_tpu.suites import workloads as wl
from jepsen_tpu.testing import (
    BankClient, G2Client, MonotonicClient, QueueClient, SequentialClient,
    SharedBank, SharedKV, SharedMonotonic, SharedQueue, noop_test)


def run_test(client, generator, checker, **over):
    t = noop_test()
    t.update({
        # clients-routing: without it the nemesis process also draws from
        # the workload generator (same idiom the reference requires)
        "client": client,
        "generator": gen.clients(generator),
        "checker": checker,
        "store-dir": None,
        "name": over.pop("name", "workload-test"),
    })
    t.update(over)
    return core.run(t)


class TestBank:
    def gen(self):
        mix = gen.mix([wl.bank_read, wl.bank_diff_transfer(5)])
        return gen.limit(200, mix)

    def test_atomic_bank_valid(self):
        bank = SharedBank(5, 10)
        t = run_test(BankClient(bank), self.gen(),
                     wl.bank_checker(5, 50), name="bank")
        assert t["results"]["valid"] is True
        # sanity: reads actually happened
        assert any(o.f == "read" and o.is_ok for o in t["history"])

    def test_broken_bank_detected(self):
        bank = SharedBank(5, 10)
        t = run_test(BankClient(bank, broken=True), self.gen(),
                     wl.bank_checker(5, 50), name="bank-broken")
        assert t["results"]["valid"] is False
        kinds = {b["type"] for b in t["results"]["bad-reads"]}
        assert kinds & {"wrong-total", "negative-value"}


class TestMonotonic:
    def gen(self):
        adds = gen.limit(100, lambda test, p: {"f": "add", "value": None})
        final = gen.once({"f": "read", "value": None})
        return gen.phases(adds, final)

    def test_monotonic_valid(self):
        tbl = SharedMonotonic()
        t = run_test(MonotonicClient(tbl), self.gen(),
                     wl.monotonic_checker(), name="monotonic")
        assert t["results"]["valid"] is True, t["results"]

    def test_skewed_timestamps_detected(self):
        tbl = SharedMonotonic()
        t = run_test(MonotonicClient(tbl, broken=True), self.gen(),
                     wl.monotonic_checker(), name="monotonic-broken")
        assert t["results"]["valid"] is False
        assert t["results"]["order-by-errors"]

    def test_never_read_is_unknown(self):
        tbl = SharedMonotonic()
        t = run_test(MonotonicClient(tbl),
                     gen.limit(10, lambda _t, _p: {"f": "add",
                                                   "value": None}),
                     wl.monotonic_checker(), name="monotonic-noread")
        assert t["results"]["valid"] == "unknown"


class TestSequential:
    def test_ordered_writes_valid(self):
        kv = SharedKV()
        t = run_test(SequentialClient(kv),
                     gen.time_limit(1.0, gen.stagger(
                         0.001, wl.sequential_gen(2))),
                     wl.SequentialChecker(),
                     name="sequential", **{"key-count": 5,
                                           "concurrency": 5})
        assert t["results"]["valid"] is True, t["results"]

    def test_reversed_writes_detected(self):
        # reversed subkey writes + concurrent readers -> trailing nils;
        # the race is probabilistic, so allow a few attempts
        for _ in range(4):
            kv = SharedKV()
            t = run_test(SequentialClient(kv, broken=True),
                         gen.time_limit(1.5, wl.sequential_gen(2)),
                         wl.SequentialChecker(),
                         name="sequential-broken", **{"key-count": 8,
                                                      "concurrency": 5})
            if t["results"]["bad-count"] >= 1:
                break
        assert t["results"]["bad-count"] >= 1
        assert t["results"]["valid"] is False

    def test_trailing_nil(self):
        assert wl.trailing_nil(["b", None])
        assert not wl.trailing_nil([None, "a"])
        assert not wl.trailing_nil(["a", "b"])
        assert not wl.trailing_nil([None, None])


class TestG2:
    def test_serializable_valid(self):
        t = run_test(G2Client(), gen.time_limit(1.0, wl.g2_gen()),
                     wl.g2_checker(), name="g2",
                     concurrency=4)
        res = t["results"]
        assert res["valid"] is True
        assert res["key-count"] > 0

    def test_racy_inserts_detected(self):
        t = run_test(G2Client(broken=True),
                     gen.time_limit(1.5, wl.g2_gen()),
                     wl.g2_checker(), name="g2-broken",
                     concurrency=4)
        assert t["results"]["valid"] is False
        assert t["results"]["illegal-count"] >= 1


class TestQueueWorkload:
    def gen(self):
        q = gen.queue_gen()
        return gen.phases(gen.limit(150, q),
                          gen.limit(80, {"f": "dequeue"}))

    def test_fifo_queue_valid(self):
        q = SharedQueue()
        t = run_test(QueueClient(q), self.gen(), total_queue(),
                     name="queue")
        assert t["results"]["valid"] is True, t["results"]

    def test_lost_enqueues_detected(self):
        q = SharedQueue()
        t = run_test(QueueClient(q, broken=True), self.gen(),
                     total_queue(), name="queue-broken")
        res = t["results"]
        assert res["valid"] is False
        assert res.get("lost") or res.get("lost-count")
