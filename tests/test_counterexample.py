"""Counterexample artifact tests (reference checker.clj:96-103: on
valid:false knossos renders linear.svg into the store)."""

import os

import pytest

jax = pytest.importorskip("jax")

from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker.counterexample import analysis, render_linear_svg
from jepsen_tpu.checker.wgl import linearizable
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister
from jepsen_tpu.models.core import CAS_REGISTER_KERNEL
from jepsen_tpu.ops import pack_history


def _failing_history():
    rows = [
        (0, "invoke", "write", 1), (0, "ok", "write", 1),
        (1, "invoke", "cas", (1, 2)), (1, "ok", "cas", (1, 2)),
        (2, "invoke", "read", None),
        (3, "invoke", "write", 3), (3, "info", "write", 3),
        (2, "ok", "read", 1),
    ]
    h = History()
    for i, (p, t, f, v) in enumerate(rows):
        h.append(Op(type=t, f=f, value=v, process=p, time=i))
    return h


def _valid_history():
    h = History()
    h.append(Op(type="invoke", f="write", value=1, process=0, time=0))
    h.append(Op(type="ok", f="write", value=1, process=0, time=1))
    return h


class TestLinearSvg:
    def test_failing_history_writes_artifact(self, tmp_path):
        test = {"store-dir": str(tmp_path)}
        out = linearizable(CASRegister()).check(test, _failing_history())
        assert out["valid"] is False
        assert out["counterexample"] == "linear.svg"
        svg = (tmp_path / "linear.svg").read_text()
        assert svg.startswith("<svg")
        assert "frontier" in svg
        # the "why": the stale read is blocked from every reachable state
        assert "blocked" in svg
        assert "read 1" in svg

    def test_valid_history_writes_nothing(self, tmp_path):
        test = {"store-dir": str(tmp_path)}
        out = linearizable(CASRegister()).check(test, _valid_history())
        assert out["valid"] is True
        assert not (tmp_path / "linear.svg").exists()

    def test_no_store_dir_is_fine(self):
        out = linearizable(CASRegister()).check({}, _failing_history())
        assert out["valid"] is False
        assert "counterexample" not in out

    def test_device_backend_renders_too(self, tmp_path):
        test = {"store-dir": str(tmp_path)}
        out = linearizable(CASRegister(), backend="tpu").check(
            test, _failing_history())
        assert out["valid"] is False
        if out.get("valid") is not UNKNOWN:
            assert (tmp_path / "linear.svg").exists()

    def test_device_refutation_renders_without_cpu_research(
            self, tmp_path, monkeypatch):
        # The device search ships its last living pool's (k, state)
        # configs off-device as final-states, so rendering a device
        # refutation never re-runs the CPU engine — check_packed is
        # monkeypatched to raise to prove it (at 100k+ ops a CPU
        # re-check could dwarf the device search; see the slow tier)
        import jepsen_tpu.checker.wgl as wgl_mod
        from jepsen_tpu.checker.tpu import check_history_tpu
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(600, n_procs=4, n_vals=8, seed=9)
        rows = list(h)
        t = rows[120].time
        rows = (rows[:120]
                + [Op(type="invoke", f="read", value=None, process=9,
                      time=t),
                   Op(type="ok", f="read", value="NEVER", process=9,
                      time=t + 1)]
                + rows[120:])
        bad = History.of(rows)
        direct = check_history_tpu(bad, CASRegister())
        assert direct["valid"] is False
        assert direct.get("final-states"), direct

        def boom(*a, **k):
            raise AssertionError("render re-ran the CPU engine")

        monkeypatch.setattr(wgl_mod, "check_packed", boom)
        test = {"store-dir": str(tmp_path)}
        out = linearizable(CASRegister(), backend="tpu").check(test, bad)
        assert out["valid"] is False
        assert out.get("counterexample-error") is None
        assert (tmp_path / "linear.svg").exists()
        assert out.get("configs")  # frontier states, device-sourced

    @pytest.mark.slow
    def test_100k_device_refutation_renders_in_one_pass(
            self, tmp_path, monkeypatch):
        import jepsen_tpu.checker.wgl as wgl_mod
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(100_000, n_procs=5, n_vals=16,
                                      seed=4, crash_p=0.0002)
        rows = list(h)
        t = rows[400].time
        rows = (rows[:400]
                + [Op(type="invoke", f="read", value=None, process=9,
                      time=t),
                   Op(type="ok", f="read", value="NEVER", process=9,
                      time=t + 1)]
                + rows[400:])
        bad = History.of(rows)

        def boom(*a, **k):
            raise AssertionError("render re-ran the CPU engine")

        monkeypatch.setattr(wgl_mod, "check_packed", boom)
        test = {"store-dir": str(tmp_path)}
        out = linearizable(CASRegister(), backend="tpu").check(test, bad)
        assert out["valid"] is False
        assert (tmp_path / "linear.svg").exists()


class TestAnalysis:
    def test_structure(self):
        p = pack_history(_failing_history(), CAS_REGISTER_KERNEL)
        from jepsen_tpu.checker.wgl import check_packed
        res = check_packed(p, CAS_REGISTER_KERNEL)
        assert res["valid"] is False
        a = analysis(p, CAS_REGISTER_KERNEL, res)
        roles = {r["role"] for r in a["ops"]}
        assert "frontier" in roles and "linearized" in roles
        assert "crashed" in roles          # the crashed write is optional
        # states are human values, not interned ids
        assert set(a["frontier-states"]) == {"2", "3"}
        frontier = [r for r in a["ops"] if r["role"] == "frontier"][0]
        assert frontier["note"].startswith("blocked from every")

    def test_final_path_is_a_real_linearization(self):
        from jepsen_tpu.checker.counterexample import witness_prefix
        from jepsen_tpu.models.core import is_inconsistent
        p = pack_history(_failing_history(), CAS_REGISTER_KERNEL)
        order = witness_prefix(p, CAS_REGISTER_KERNEL)
        assert order                      # non-empty maximal path
        # replay the path through the object model: every step legal
        m = CASRegister()
        for j in order:
            inv_op, _ = p.ops[j]
            val = inv_op.value
            if inv_op.f == "read":
                comp = p.ops[j][1]
                if comp is not None and comp.value is not None:
                    val = comp.value
            m = m.step(inv_op.replace(value=val))
            assert not is_inconsistent(m), (j, inv_op)

    def test_result_carries_final_path(self, tmp_path):
        test = {"store-dir": str(tmp_path)}
        out = linearizable(CASRegister()).check(test, _failing_history())
        assert out["valid"] is False
        assert out["final-path"]          # e.g. ['write 1', 'cas (1, 2)']
        # knossos :configs equivalent, truncated to 10 (checker.clj:104-107)
        assert out["configs"] and len(out["configs"]) <= 10
        svg = (tmp_path / "linear.svg").read_text()
        assert "maximal path" in svg

    def test_harvest_when_states_missing(self, tmp_path):
        p = pack_history(_failing_history(), CAS_REGISTER_KERNEL)
        res = {"valid": False, "max-linearized-prefix": 2}
        a = render_linear_svg(p, CAS_REGISTER_KERNEL, res,
                              str(tmp_path / "x.svg"))
        assert a["frontier-states"]
        assert (tmp_path / "x.svg").exists()
