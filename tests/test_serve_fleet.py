"""Fleet-backed serving tests (doc/serve.md "Fleet-backed serving").

The zero-lost-verdict contract: a FleetPlacer shards coalesced gangs
over an elastic host set; a host killed mid-gang re-meshes onto the
survivors with the orphaned lanes' carries merged back; below minimum
capacity every lane fails over to the serial escalation path — and in
all cases every accepted request answers a verdict identical to the
offline analyze path, with zero breaker trips and zero poison
misclassification. JTPU_SERVE_FLEET=0 restores the single-host daemon
byte-identically (the kill-switch identity leg).

These tests drive the LocalHost backend (in-process CPU-simulated
mesh); the real 2-process ProcHost path is exercised by
tools/chaos_matrix.py serve-fleet-host-kill and tools/serve_gate.py.
"""

import threading
import time

import numpy as np
import pytest

from jepsen_tpu import fleet as fleet_ns
from jepsen_tpu import serve as serve_ns
from jepsen_tpu.checker import tpu as T
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.ops.encode import pack_with_init

from tests.test_serve import (_VERDICT_KEYS, _conc_ops, _daemon,
                              _offline, _ops, _wait_done)

pytestmark = pytest.mark.serve


def _fleet_daemon(tmp_path, hosts=2, **cfg):
    cfg.setdefault("fleet_hosts", hosts)
    cfg.setdefault("fleet_backend", "local")
    cfg.setdefault("batch_wait_ms", 150.0)
    cfg.setdefault("workers", 1)
    return _daemon(tmp_path, **cfg)


def _submit_burst(d, histories, tenants=("t0", "t1", "t2")):
    rids = []
    for i, ops in enumerate(histories):
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": ops,
                                  "tenant": tenants[i % len(tenants)]})
        assert code == 202, body
        rids.append(body["id"])
    return rids


class TestFleetPlacement:
    def test_gang_over_fleet_matches_offline(self, tmp_path):
        """A multi-tenant same-bucket burst dispatches as ONE gang
        sharded over the fleet hosts, and every verdict equals the
        offline analyze path's."""
        histories = [_ops(3), _ops(3, value=50), _ops(3, value=90)]
        d = _fleet_daemon(tmp_path)
        assert d.placer is not None
        d.start()
        try:
            assert len(d.placer.hosts) == 2
            assert d.placer.live() == 2
            rids = _submit_burst(d, histories)
            docs = [_wait_done(d, rid) for rid in rids]
            for ops, doc in zip(histories, docs):
                offline = _offline(ops)
                for key in _VERDICT_KEYS:
                    assert doc["result"].get(key) == offline.get(key), \
                        (key, doc["result"])
            assert d.placer.stats["gangs"] >= 1
            assert d.placer.stats["rounds"] >= 1
            hz = d.healthz()
            assert hz["fleet"]["hosts"] == 2
            assert hz["fleet"]["live"] == 2
            assert hz["fleet"]["backend"] == "local"
        finally:
            d.stop()

    def test_single_request_routes_through_fleet(self, tmp_path):
        """Even a gang of one is placed on the fleet (the placer, not
        the gang size, selects the dispatch path)."""
        d = _fleet_daemon(tmp_path)
        d.start()
        try:
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops(3)})
            assert code == 202
            doc = _wait_done(d, body["id"])
            assert doc["result"]["valid"] is True
            assert d.placer.stats["gangs"] == 1
        finally:
            d.stop()


class TestHostLossFailover:
    def test_host_kill_mid_gang_zero_lost_verdicts(self, tmp_path,
                                                   monkeypatch):
        """The tentpole contract: a host killed mid-gang triggers a
        re-mesh; the orphaned lanes' frontier carries merge back and
        finish on the surviving host — every verdict delivered,
        offline-identical, ZERO breaker trips, ZERO poison."""
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "1")
        histories = [_conc_ops(24, 11), _conc_ops(24, 12, value_base=60),
                     _conc_ops(24, 13, value_base=120)]
        d = _fleet_daemon(tmp_path)
        killed = []

        def kill_second_host(round_idx, hosts):
            if not killed:
                hosts[-1].kill()
                killed.append(round_idx)

        d.placer.on_round = kill_second_host
        d.start()
        try:
            # segment_iters=1 gives the ladder several merge barriers
            # (rounds), so the kill lands mid-gang, not post-gang
            rids = _submit_burst(d, histories)
            docs = [_wait_done(d, rid) for rid in rids]
            for ops, doc in zip(histories, docs):
                offline = _offline(ops)
                for key in _VERDICT_KEYS:
                    assert doc["result"].get(key) == offline.get(key), \
                        (key, doc["result"])
            assert killed, "chaos seam never fired"
            assert d.placer.stats["host-losses"] >= 1
            assert d.placer.stats["remeshes"] >= 1
            assert d.placer.live() == 1
            assert d.stats["poisoned"] == 0
            snap = d.breaker.snapshot()
            assert all(r["fails"] == 0 for r in snap.values()), snap
            assert all(r["state"] == "closed"
                       for r in snap.values()), snap
        finally:
            d.stop()

    def test_all_hosts_lost_fails_over_to_serial(self, tmp_path,
                                                 monkeypatch):
        """Below minimum capacity (every host gone) the lanes answer
        fleet-lost and the daemon's serial escalation path still
        delivers offline-identical verdicts — zero lost verdicts even
        with zero hosts."""
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "1")
        histories = [_conc_ops(24, 21), _conc_ops(24, 22, value_base=60)]
        d = _fleet_daemon(tmp_path)

        def kill_everything(round_idx, hosts):
            for h in hosts:
                h.kill()

        d.placer.on_round = kill_everything
        d.start()
        try:
            rids = _submit_burst(d, histories)
            docs = [_wait_done(d, rid) for rid in rids]
            for ops, doc in zip(histories, docs):
                offline = _offline(ops)
                for key in _VERDICT_KEYS:
                    assert doc["result"].get(key) == offline.get(key), \
                        (key, doc["result"])
            assert d.placer.live() == 0
            assert d.stats["poisoned"] == 0
            snap = d.breaker.snapshot()
            assert all(r["fails"] == 0 for r in snap.values()), snap
        finally:
            d.stop()

    def test_fleet_ladder_direct_host_loss(self):
        """check_packed_gang_fleet unit leg: kill one LocalHost from
        the chaos seam mid-collect; verdicts match the local gang
        path's and the stats record the loss + remesh."""
        histories = [_conc_ops(24, 31), _conc_ops(24, 32, value_base=60)]
        pks, kernel = [], None
        for ops in histories:
            pk = pack_with_init(History.of(ops), CASRegister())
            pks.append(pk[0])
            kernel = pk[1]
        h0 = fleet_ns.LocalHost("h0")
        h1 = fleet_ns.LocalHost("h1")

        def chaos(ctx):
            raise fleet_ns.HostLostError("host h1 is gone (chaos)")

        h1.chaos = chaos
        h0.start(None, None)
        h1.start(None, None)
        stats: dict = {}
        trail: list = []
        out = T.check_packed_gang_fleet(pks, kernel, [h0, h1],
                                        stats=stats, trail=trail)
        serial = T.check_packed_gang(pks, kernel)
        for got, want in zip(out, serial):
            for key in _VERDICT_KEYS:
                assert got.get(key) == want.get(key), (key, got, want)
            assert got.get("fleet") is True
        assert stats.get("host-losses", 0) >= 1
        assert stats.get("remeshes", 0) >= 1
        assert any(ev["event"] == "host-lost" for ev in trail)

    def test_dcn_retry_succeeds_without_breaker_impact(self, tmp_path):
        """A transient interconnect blip (first collect raises a
        connection error) is retried in place by the fleet ladder:
        the verdict lands, the breaker stays closed at zero fails,
        and the retry is counted — not a host loss, not a poison."""
        d = _fleet_daemon(tmp_path)
        d.start()
        try:
            blipped = []

            def blip_once(ctx):
                if not blipped:
                    blipped.append(ctx)
                    raise RuntimeError(
                        "connection reset by peer (injected DCN blip)")

            d.placer.hosts[0].chaos = blip_once
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops(3)})
            assert code == 202
            doc = _wait_done(d, body["id"])
            offline = _offline(_ops(3))
            for key in _VERDICT_KEYS:
                assert doc["result"].get(key) == offline.get(key)
            assert blipped, "chaos seam never fired"
            assert d.placer.stats["dcn-retries"] >= 1
            assert d.placer.stats["host-losses"] == 0
            assert d.stats["poisoned"] == 0
            snap = d.breaker.snapshot()
            assert all(r["fails"] == 0 for r in snap.values()), snap
        finally:
            d.stop()

    def test_poison_still_raises_through_fleet(self, tmp_path):
        """A deterministic failure (OOM-class) on a fleet host is NOT
        absorbed as a host loss: it raises to bisect_poison exactly as
        the local gang path does, so fault isolation composes with
        fleet placement."""
        histories = [_conc_ops(24, 41), _conc_ops(24, 42, value_base=60)]
        pks, kernel = [], None
        for ops in histories:
            pk = pack_with_init(History.of(ops), CASRegister())
            pks.append(pk[0])
            kernel = pk[1]
        h0 = fleet_ns.LocalHost("h0")

        def oom(ctx):
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")

        h0.chaos = oom
        h0.start(None, None)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            T.check_packed_gang_fleet(pks, kernel, [h0])


class TestFleetDrain:
    def test_drain_with_fleet_gang_in_flight(self, tmp_path,
                                             monkeypatch):
        """POST /drain with a fleet-dispatched gang in flight — and a
        worker host dying during the drain window — still finishes the
        gang (zero lost verdicts), leaves the queued remainder
        journaled, and completes cleanly."""
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "1")
        histories = [_conc_ops(24, 51), _conc_ops(24, 52, value_base=60)]
        d = _fleet_daemon(tmp_path, queue_max=8)
        gate = threading.Event()
        killed = []

        def slow_then_kill(round_idx, hosts):
            gate.set()
            if not killed:
                killed.append(round_idx)
                hosts[-1].kill()
            time.sleep(0.05)   # stretch the gang across the drain call

        d.placer.on_round = slow_then_kill
        d.start()
        try:
            rids = _submit_burst(d, histories)
            assert gate.wait(20.0), "gang never dispatched"
            # queue one more while the gang holds the only worker:
            # drain must leave it journaled, not run it
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops(2),
                                      "tenant": "late"})
            assert code == 202
            late_rid = body["id"]
            out = d.drain(timeout_s=60.0)
            assert out["drained"] is True
            assert out["inflight-remaining"] == 0
            # the in-flight gang finished with offline verdicts
            for ops, rid in zip(histories, rids):
                doc = d.status(rid)
                assert doc["state"] == "done", doc
                offline = _offline(ops)
                for key in _VERDICT_KEYS:
                    assert doc["result"].get(key) == offline.get(key)
            # the late request stayed queued — journaled for replay
            assert d.status(late_rid)["state"] == "queued"
            pending, _ = serve_ns.RequestJournal.replay(d.journal.path)
            assert [p["id"] for p in pending] == [late_rid]
        finally:
            d.stop()


class TestFleetKillSwitch:
    def test_env_zero_overrides_explicit_hosts(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("JTPU_SERVE_FLEET", "0")
        d = _fleet_daemon(tmp_path, hosts=2)
        assert d.config.fleet_enabled is False
        assert d.placer is None
        d.stop()

    def test_no_fleet_is_byte_identical_single_host(self, tmp_path):
        """The kill-switch identity test: with no --fleet the daemon
        constructs NO placer, routes through the identical single-host
        paths, publishes no fleet keys anywhere, and serves the same
        verdicts."""
        import json
        import os
        d = _daemon(tmp_path, workers=1)
        assert d.config.fleet_enabled is False
        assert d.placer is None
        assert d._fleet_width() == 1
        # capacity budget degenerates to the single-host budget
        assert d._capacity_budget() == d._budget()
        d.start()
        try:
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops(3)})
            assert code == 202
            doc = _wait_done(d, body["id"])
            offline = _offline(_ops(3))
            for key in _VERDICT_KEYS:
                assert doc["result"].get(key) == offline.get(key)
            hz = d.healthz()
            assert "fleet" not in hz
            d._publish(force=True)
            with open(os.path.join(d.config.root,
                                   serve_ns.PROGRESS_NAME)) as f:
                prog = json.load(f)
            assert "fleet-hosts" not in prog["serve"]
            assert "fleet-live" not in prog["serve"]
            assert "remeshes" not in prog["serve"]
            assert "rate-limited" not in prog["serve"]
            # no fleet host dirs were created either
            assert not [p for p in os.listdir(d.config.root)
                        if p.startswith("fleet-host-")]
        finally:
            d.stop()


class TestFleetGangWire:
    def test_gang_request_roundtrip(self, tmp_path):
        """save_gang_request/load_gang_request preserve stacked cols,
        batched carry (shapes AND dtypes), kernel name and meta."""
        pks, kernel = [], None
        for ops in (_ops(3), _ops(3, value=9)):
            pk = pack_with_init(History.of(ops), CASRegister())
            pks.append(pk[0])
            kernel = pk[1]
        breq = max(T._bucket(p.n_required) for p in pks)
        crw = max(T._crash_width(p.n - p.n_required) for p in pks)
        cols = [T._split_packed(p, breq, crw, kernel) for p in pks]
        arrays = [np.stack([np.asarray(c[name]) for c in cols])
                  for name in T._COLS]
        cr_pad = int(cols[0]["cf"].shape[0])
        carry = tuple(
            np.stack(lanes) for lanes in zip(*(
                T._carry0_host(32, 32, cr_pad, c["ini"], int(c["nr"]))
                for c in cols)))
        path = str(tmp_path / "greq_1.npz")
        fleet_ns.save_gang_request(path, arrays, carry, kernel.name,
                                   seg_iters=64, capacity=32,
                                   window=32, expand=4, round=0,
                                   trace="ab" * 16)
        cols2, carry2, kname, meta = fleet_ns.load_gang_request(path)
        assert kname == kernel.name
        assert meta["seg_iters"] == 64 and meta["round"] == 0
        assert meta["trace"] == "ab" * 16
        for a, b in zip(arrays, cols2):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(carry, carry2):
            assert np.asarray(a).shape == np.asarray(b).shape
            assert np.asarray(a).dtype == np.asarray(b).dtype
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_load_carry_keeps_batched_lanes(self, tmp_path):
        """load_carry must NOT collapse a gang's (G,)-shaped flag and
        level lanes to scalars (np.bool_ on a 2-lane array would even
        raise) — only dtypes are pinned."""
        pks = []
        for ops in (_ops(3), _ops(3, value=9)):
            pk = pack_with_init(History.of(ops), CASRegister())
            pks.append(pk[0])
            kernel = pk[1]
        breq = max(T._bucket(p.n_required) for p in pks)
        crw = max(T._crash_width(p.n - p.n_required) for p in pks)
        cols = [T._split_packed(p, breq, crw, kernel) for p in pks]
        cr_pad = int(cols[0]["cf"].shape[0])
        carry = tuple(
            np.stack(lanes) for lanes in zip(*(
                T._carry0_host(32, 32, cr_pad, c["ini"], int(c["nr"]))
                for c in cols)))
        path = str(tmp_path / "gresp_1.npz")
        fleet_ns.save_carry(path, carry, gang=2)
        got, meta = fleet_ns.load_carry(path)
        assert meta["gang"] == 2
        for a, b in zip(carry, got):
            assert np.asarray(a).shape == np.asarray(b).shape, \
                "batched lane collapsed to scalar"
        assert np.asarray(got[5]).dtype == np.bool_
        assert np.asarray(got[8]).dtype == np.int32

    def test_localhost_gang_segment_matches_batch_jit(self):
        """LocalHost.submit_gang/collect_gang runs exactly the vmapped
        batch segment the local gang path runs."""
        pks, kernel = [], None
        for ops in (_ops(3), _ops(3, value=9)):
            pk = pack_with_init(History.of(ops), CASRegister())
            pks.append(pk[0])
            kernel = pk[1]
        breq = max(T._bucket(p.n_required) for p in pks)
        crw = max(T._crash_width(p.n - p.n_required) for p in pks)
        cols = [T._split_packed(p, breq, crw, kernel) for p in pks]
        arrays = [np.stack([np.asarray(c[name]) for c in cols])
                  for name in T._COLS]
        cr_pad = int(cols[0]["cf"].shape[0])
        carry = tuple(
            np.stack(lanes) for lanes in zip(*(
                T._carry0_host(32, 32, cr_pad, c["ini"], int(c["nr"]))
                for c in cols)))
        h = fleet_ns.LocalHost("h0")
        h.start(None, None)
        h.submit_gang(arrays, carry, kernel, 64, (32, 32, 4), 0)
        out, secs = h.collect_gang(30.0)
        fn = T._jit_batch_segment(T._kernel_key(kernel), 32, 32, 4,
                                  T._unroll_factor())
        want = tuple(np.asarray(x)
                     for x in fn(*arrays, np.int32(64), carry))
        for a, b in zip(want, out):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestFleetObservability:
    def test_watch_line_renders_fleet_and_rate_bits(self, tmp_path):
        """The watch/live serve line grows `fleet N host(s)` and
        `rate-limited M` bits when (and ONLY when — see the
        kill-switch identity test) the features are on."""
        from jepsen_tpu.obs import observatory
        d = _fleet_daemon(tmp_path, rate_limit=100.0)
        d.start()
        try:
            d.placer.stats["remeshes"] = 3
            d.stats["rate-limited"] = 2
            d._publish(force=True)
            p = observatory.read_progress(d.config.root)
            assert p["serve"]["fleet-hosts"] == 2
            assert p["serve"]["fleet-live"] == 2
            line = observatory.format_status(p)
            assert line.startswith("# serve: ")
            assert "fleet 2/2 host(s)" in line
            assert "remesh 3" in line
            assert "rate-limited 2" in line
        finally:
            d.stop()

    def test_healthz_fleet_section(self, tmp_path):
        d = _fleet_daemon(tmp_path)
        d.start()
        try:
            hz = d.healthz()
            assert hz["fleet"]["backend"] == "local"
            assert hz["fleet"]["hosts"] == 2
            for key in ("gangs", "rounds", "remeshes", "host-losses",
                        "dcn-retries"):
                assert hz["fleet"][key] == 0
        finally:
            d.stop()
