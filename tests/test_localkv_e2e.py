"""Tier-3 end-to-end: the full harness against REAL local processes.

The reference keeps cluster-dependent tests that require live daemons and
verify per-node artifacts landed in the store
(jepsen/test/jepsen/core_test.clj:30-84 ssh-test, control_test.clj:5-8).
This is that tier on localhost: N kvnode daemons (real pids, real TCP),
the LOCAL control plane, the complete core.run lifecycle — daemon start
via start-stop-daemon, SIGSTOP hammer-time, log snarf, store artifacts,
checking."""

import json
import os
import re

import pytest

from jepsen_tpu import core
from jepsen_tpu.suites.localkv import localkv_test, localkv_unsafe_test


@pytest.fixture
def store_root(tmp_path):
    return str(tmp_path / "store")


class TestLocalKVE2E:
    def test_full_lifecycle_real_processes(self, store_root, tmp_path):
        test = localkv_test({"time-limit": 5, "nemesis-period": 1.5})
        test["store-dir"] = str(tmp_path / "run")
        out = core.run(test)

        # the service is linearizable by construction; a False here is a
        # real harness/daemon bug
        assert out["results"]["valid"] is True, out["results"]
        assert len(out["history"]) > 50

        d = test["store-dir"]
        files = set(os.listdir(d))
        assert "history.jsonl" in files and "results.json" in files
        with open(os.path.join(d, "results.json")) as fh:
            assert json.load(fh)["valid"] is True

        # per-node snarfed daemon logs, containing REAL pids that were
        # alive during the run (start-stop-daemon wrote the pidfiles)
        pids = set()
        for node in test["nodes"]:
            log_path = os.path.join(d, node, "kv.log")
            assert os.path.exists(log_path), files
            body = open(log_path).read()
            pids.update(int(m) for m in re.findall(r"kvnode\[(\d+)\]",
                                                   body))
            assert "listening on" in body
        assert len(pids) >= len(test["nodes"])  # one real pid per daemon

        # the nemesis actually froze processes mid-run
        nem_ops = [o for o in out["history"]
                   if o.process == "nemesis" and o.value is not None]
        assert any("paused" in str(o.value) for o in nem_ops)

    def test_unsafe_read_local_is_refuted(self, tmp_path):
        test = localkv_unsafe_test({})
        test["store-dir"] = str(tmp_path / "run")
        out = core.run(test)
        # deterministic: the backup read is invoked after write(2)
        # completed but its replica still holds 1 (1 s lag vs 2.5 s
        # settle) — a stale read the checker must refute
        assert out["results"]["valid"] is False, out["results"]
        lin = out["results"]["linear"]
        assert lin["valid"] is False
        assert lin.get("counterexample") == "linear.svg"
        assert os.path.exists(os.path.join(test["store-dir"],
                                           "linear.svg"))
        reads = [o for o in out["history"]
                 if o.f == "read" and o.type == "ok"]
        assert reads and reads[0].value == 1  # the stale value, on cue
