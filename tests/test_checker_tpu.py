"""TPU (batched JAX) linearizability backend tests.

Runs on the virtual 8-device CPU mesh (conftest.py). The CPU WGL from
jepsen_tpu.checker.wgl — itself validated against a brute-force oracle in
test_linearizable.py — is the reference semantics here.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker.tpu import (
    check_history_tpu, check_keyed_tpu, check_packed_tpu)
from jepsen_tpu.checker.wgl import check_packed, linearizable
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister, Mutex
from jepsen_tpu.models.core import CAS_REGISTER_KERNEL, MUTEX_KERNEL
from jepsen_tpu.ops import pack_history
from jepsen_tpu.testing import wide_history

from test_linearizable import H, random_register_history


class TestGoldenTPU:
    def test_sequential_valid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 0))
        assert check_history_tpu(h, CASRegister())["valid"] is True

    def test_sequential_invalid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        assert check_history_tpu(h, CASRegister())["valid"] is False

    def test_cas_then_stale_read_invalid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "cas", (0, 1)), (1, "ok", "cas", (0, 1)),
              (2, "invoke", "read", None), (2, "ok", "read", 0))
        assert check_history_tpu(h, CASRegister())["valid"] is False

    def test_crashed_write_may_apply(self):
        h = H((0, "invoke", "write", 1),
              (0, "info", "write", 1),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        assert check_history_tpu(h, CASRegister())["valid"] is True

    def test_crashed_write_applies_late(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "write", 9),
              (2, "invoke", "read", None), (2, "ok", "read", 0),
              (3, "invoke", "read", None), (3, "ok", "read", 9))
        assert check_history_tpu(h, CASRegister())["valid"] is True

    def test_mutex(self):
        bad = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None),
                (1, "invoke", "acquire", None), (1, "ok", "acquire", None))
        assert check_history_tpu(bad, Mutex())["valid"] is False
        good = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None),
                 (0, "invoke", "release", None), (0, "ok", "release", None),
                 (1, "invoke", "acquire", None), (1, "ok", "acquire", None))
        assert check_history_tpu(good, Mutex())["valid"] is True

    def test_empty(self):
        assert check_history_tpu(H(), CASRegister())["valid"] is True

    def test_nonnil_initial_value(self):
        h = H((0, "invoke", "read", None), (0, "ok", "read", 7))
        assert check_history_tpu(h, CASRegister(7))["valid"] is True
        assert check_history_tpu(h, CASRegister(8))["valid"] is False


class TestInitStates:
    def test_keyed_nonnil_initial_value(self):
        # regression: keyed path must honor the model instance's init state
        h = H((0, "invoke", "read", None), (0, "ok", "read", 7))
        out = check_keyed_tpu({0: h}, CASRegister(7))
        assert out["results"][0]["valid"] is True
        out8 = check_keyed_tpu({0: h}, CASRegister(8))
        assert out8["results"][0]["valid"] is False

    def test_locked_mutex_initial_state(self):
        # regression: Mutex(True) must start locked on the device path
        h = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None))
        assert check_history_tpu(h, Mutex(True))["valid"] is False
        assert check_history_tpu(h, Mutex(False))["valid"] is True

    def test_window_over_max_rejected(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0))
        with pytest.raises(ValueError):
            check_history_tpu(h, CASRegister(), window=256)

    def test_window_64_accepted(self):
        # the multi-word mask lifted the cap: 64 and 128 are legal widths.
        # capacity must be explicit — with capacity=None the ladder picks
        # its own windows and the parameter is only validated, not used.
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 0))
        assert check_history_tpu(h, CASRegister(), capacity=64,
                                 window=64)["valid"] is True
        assert check_history_tpu(h, CASRegister(), capacity=64,
                                 window=128)["valid"] is True


class TestAgainstCPUOracle:
    def test_random_histories_agree_with_jit_algorithm(self):
        # device pool search vs the INDEPENDENT just-in-time algorithm
        # (not just the repo's own WGL) — a true differential oracle
        from jepsen_tpu.checker.jitlin import check_jit_packed
        rng = random.Random(31)
        for i in range(60):
            h = random_register_history(rng, n_procs=4, n_ops=9, n_vals=3,
                                        crash_p=0.15)
            p = pack_history(h, CAS_REGISTER_KERNEL)
            want = check_jit_packed(p, CAS_REGISTER_KERNEL)["valid"]
            got = check_packed_tpu(p, CAS_REGISTER_KERNEL,
                                   capacity=512)["valid"]
            assert got is want or got is UNKNOWN, (i, want, got)

    def test_random_histories_agree(self):
        rng = random.Random(7)
        mismatches = []
        for i in range(120):
            h = random_register_history(rng, n_procs=4, n_ops=8, n_vals=3)
            p = pack_history(h, CAS_REGISTER_KERNEL)
            want = check_packed(p, CAS_REGISTER_KERNEL)["valid"]
            got = check_packed_tpu(p, CAS_REGISTER_KERNEL,
                                   capacity=512)["valid"]
            if got is not want and got is not UNKNOWN:
                mismatches.append((i, want, got))
        assert not mismatches

    def test_longer_histories_agree(self):
        rng = random.Random(99)
        for _ in range(10):
            h = random_register_history(rng, n_procs=5, n_ops=60, n_vals=4,
                                        crash_p=0.05)
            p = pack_history(h, CAS_REGISTER_KERNEL)
            want = check_packed(p, CAS_REGISTER_KERNEL)["valid"]
            got = check_packed_tpu(p, CAS_REGISTER_KERNEL)["valid"]
            assert got is want or got is UNKNOWN

    def test_facade_tpu_backend(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        c = linearizable(CASRegister(), backend="tpu")
        assert c.check({}, h)["valid"] is False


class TestKeyedBatch:
    def _keyed(self, rng, n_keys):
        keyed = {}
        for k in range(n_keys):
            keyed[k] = random_register_history(
                rng, n_procs=3, n_ops=10, n_vals=3, crash_p=0.1)
        return keyed

    def test_keyed_matches_per_key_cpu(self):
        rng = random.Random(11)
        keyed = self._keyed(rng, 6)
        out = check_keyed_tpu(keyed, CASRegister(), capacity=512)
        for k, h in keyed.items():
            want = check_packed(pack_history(h, CAS_REGISTER_KERNEL),
                                CAS_REGISTER_KERNEL)["valid"]
            got = out["results"][k]["valid"]
            assert got is want or got is UNKNOWN, (k, want, got)

    def test_keyed_sharded_over_mesh(self):
        devs = jax.devices()
        assert len(devs) == 8, "conftest should force an 8-device CPU mesh"
        mesh = jax.sharding.Mesh(np.array(devs), ("keys",))
        rng = random.Random(13)
        keyed = self._keyed(rng, 16)
        out = check_keyed_tpu(keyed, CASRegister(), capacity=256, mesh=mesh)
        assert set(out["results"]) == set(keyed)
        for k, h in keyed.items():
            want = check_packed(pack_history(h, CAS_REGISTER_KERNEL),
                                CAS_REGISTER_KERNEL)["valid"]
            got = out["results"][k]["valid"]
            assert got is want or got is UNKNOWN, (k, want, got)

    def test_keyed_unsupported_op_isolated(self):
        # regression: one key with an un-encodable op must not abort the
        # batch — that key alone goes unknown
        good = H((0, "invoke", "write", 1), (0, "ok", "write", 1))
        bad = H((0, "invoke", "frobnicate", None),
                (0, "ok", "frobnicate", None))
        out = check_keyed_tpu({"g": good, "b": bad}, CASRegister())
        assert out["results"]["g"]["valid"] is True
        assert out["results"]["b"]["valid"] is UNKNOWN
        assert out["valid"] is UNKNOWN

    def test_keyed_unpadded_key_count(self):
        # key count not divisible by mesh size exercises the padding path
        devs = jax.devices()
        mesh = jax.sharding.Mesh(np.array(devs), ("keys",))
        rng = random.Random(17)
        keyed = self._keyed(rng, 5)
        out = check_keyed_tpu(keyed, CASRegister(), capacity=256, mesh=mesh)
        assert set(out["results"]) == set(keyed)


# ---------------------------------------------------------------------------
# Set / UnorderedQueue integer kernels (device path for non-register models)
# ---------------------------------------------------------------------------

from jepsen_tpu.checker.wgl import check_model          # noqa: E402
from jepsen_tpu.models import SetModel, UnorderedQueue  # noqa: E402


def random_set_history(rng, n_procs=3, n_ops=8, n_vals=4, crash_p=0.1,
                       corrupt_p=0.3):
    """Random concurrent grow-only-set history. Reads return a snapshot of
    the elements applied so far, randomly corrupted with corrupt_p."""
    h = History()
    free = list(range(n_procs))
    open_ops = {}
    applied = set()
    ops_left = n_ops
    t = 0
    while (ops_left > 0 and free) or open_ops:
        if free and ops_left > 0 and (not open_ops or rng.random() < 0.5):
            p = rng.choice(free)
            free.remove(p)
            ops_left -= 1
            if rng.random() < 0.6:
                op = Op(type="invoke", f="add", value=rng.randrange(n_vals),
                        process=p, time=t)
            else:
                op = Op(type="invoke", f="read", value=None, process=p,
                        time=t)
            h.append(op)
            open_ops[p] = op
        else:
            p = rng.choice(list(open_ops))
            inv = open_ops.pop(p)
            r = rng.random()
            if r < crash_p and inv.f == "add":
                h.append(Op(type="info", f=inv.f, value=inv.value,
                            process=p, time=t))
            else:
                if inv.f == "add":
                    applied.add(inv.value)
                    h.append(Op(type="ok", f="add", value=inv.value,
                                process=p, time=t))
                else:
                    snap = set(applied)
                    if rng.random() < corrupt_p:
                        flip = rng.randrange(n_vals)
                        snap ^= {flip}
                    h.append(Op(type="ok", f="read", value=sorted(snap),
                                process=p, time=t))
                free.append(p)
        t += 1
    return h


def random_queue_history(rng, n_procs=3, n_ops=8, n_vals=4, crash_p=0.1,
                         corrupt_p=0.2):
    """Random concurrent unordered-queue history: enqueues of small values,
    dequeues of a pending (or, with corrupt_p, arbitrary) value."""
    import collections
    h = History()
    free = list(range(n_procs))
    open_ops = {}
    pending = collections.Counter()
    ops_left = n_ops
    t = 0
    while (ops_left > 0 and free) or open_ops:
        if free and ops_left > 0 and (not open_ops or rng.random() < 0.5):
            p = rng.choice(free)
            free.remove(p)
            ops_left -= 1
            if rng.random() < 0.6 or not +pending:
                op = Op(type="invoke", f="enqueue",
                        value=rng.randrange(n_vals), process=p, time=t)
            else:
                op = Op(type="invoke", f="dequeue", value=None, process=p,
                        time=t)
            h.append(op)
            open_ops[p] = op
        else:
            p = rng.choice(list(open_ops))
            inv = open_ops.pop(p)
            r = rng.random()
            if r < crash_p and inv.f == "enqueue":
                h.append(Op(type="info", f=inv.f, value=inv.value,
                            process=p, time=t))
            else:
                if inv.f == "enqueue":
                    pending[inv.value] += 1
                    h.append(Op(type="ok", f="enqueue", value=inv.value,
                                process=p, time=t))
                else:
                    live = sorted(v for v, c in pending.items() if c > 0)
                    if live and rng.random() >= corrupt_p:
                        v = rng.choice(live)
                        pending[v] -= 1
                    else:
                        v = rng.randrange(n_vals)
                    h.append(Op(type="ok", f="dequeue", value=v,
                                process=p, time=t))
                free.append(p)
        t += 1
    return h


class TestSetKernel:
    def test_valid_and_invalid_golden(self):
        ok = H((0, "invoke", "add", 1), (0, "ok", "add", 1),
               (1, "invoke", "read", None), (1, "ok", "read", [1]))
        assert check_history_tpu(ok, SetModel())["valid"] is True
        bad = H((0, "invoke", "add", 1), (0, "ok", "add", 1),
                (1, "invoke", "read", None), (1, "ok", "read", [2]))
        assert check_history_tpu(bad, SetModel())["valid"] is False

    def test_concurrent_add_read_race(self):
        # read overlapping the add may see either set
        h = H((0, "invoke", "add", 1),
              (1, "invoke", "read", None), (1, "ok", "read", []),
              (0, "ok", "add", 1),
              (2, "invoke", "read", None), (2, "ok", "read", [1]))
        assert check_history_tpu(h, SetModel())["valid"] is True

    def test_initial_items_in_model_instance(self):
        h = H((0, "invoke", "read", None), (0, "ok", "read", [7]))
        assert check_history_tpu(h, SetModel({7}))["valid"] is True
        assert check_history_tpu(h, SetModel({8}))["valid"] is False

    def test_random_golden_vs_object_search(self):
        rng = random.Random(5)
        decided = valid = invalid = 0
        for _ in range(150):
            h = random_set_history(rng)
            want = check_model(h, SetModel())["valid"]
            got = check_history_tpu(h, SetModel(), capacity=512)["valid"]
            assert got is want or got is UNKNOWN, (want, got, list(h))
            decided += got is not UNKNOWN
            valid += want is True and got is True
            invalid += want is False and got is False
        # the device path must actually decide (in both directions), not
        # hide behind UNKNOWN
        assert decided > 100 and valid and invalid

    def test_many_unread_elements_ride_device(self):
        # 40 adds with no read used to overflow the 31-bit mask; read-
        # signature classes collapse them into ONE count field
        rows = []
        for v in range(40):
            rows += [(0, "invoke", "add", v), (0, "ok", "add", v)]
        h = H(*rows)
        r = check_history_tpu(h, SetModel())
        assert r is not None and r["valid"] is True
        assert r["backend"] == "tpu"

    def test_hundreds_of_adds_with_final_read(self):
        # the realistic sets workload (cockroach sets.clj / disque):
        # unique adds, one crashed, one final exact read
        rows = []
        for v in range(200):
            rows += [(v % 5, "invoke", "add", v), (v % 5, "ok", "add", v)]
        rows += [(9, "invoke", "add", 999), (9, "info", "add", 999)]
        final = sorted(range(200))          # crashed add not observed
        rows += [(6, "invoke", "read", None), (6, "ok", "read", final)]
        h = H(*rows)
        r = check_history_tpu(h, SetModel())
        assert r is not None and r["valid"] is True
        assert r["backend"] == "tpu"
        # lost update: drop element 77 from the read -> refuted on device
        bad = sorted(v for v in range(200) if v != 77)
        rows[-1] = (6, "ok", "read", bad)
        r2 = check_history_tpu(H(*rows), SetModel())
        assert r2 is not None and r2["valid"] is False

    def test_read_of_never_added_element_refuted(self):
        h = H((0, "invoke", "add", 1), (0, "ok", "add", 1),
              (1, "invoke", "read", None), (1, "ok", "read", [1, 999]))
        r = check_history_tpu(h, SetModel())
        assert r is not None and r["valid"] is False

    def test_distinct_signatures_overflow_falls_back(self):
        # 35 adds each followed by a prefix read: every element gets a
        # distinct read signature -> 35 classes -> > 31 bits -> fallback
        rows = []
        for v in range(35):
            rows += [(0, "invoke", "add", v), (0, "ok", "add", v),
                     (1, "invoke", "read", None),
                     (1, "ok", "read", sorted(range(v + 1)))]
        h = H(*rows)
        assert check_history_tpu(h, SetModel()) is None
        # facade still answers via the object search
        assert linearizable(SetModel(), backend="tpu").check(
            {}, h)["valid"] is True


class TestUnorderedQueueKernel:
    def test_valid_and_invalid_golden(self):
        ok = H((0, "invoke", "enqueue", 3), (0, "ok", "enqueue", 3),
               (1, "invoke", "dequeue", None), (1, "ok", "dequeue", 3))
        assert check_history_tpu(ok, UnorderedQueue())["valid"] is True
        bad = H((0, "invoke", "enqueue", 3), (0, "ok", "enqueue", 3),
                (1, "invoke", "dequeue", None), (1, "ok", "dequeue", 4))
        assert check_history_tpu(bad, UnorderedQueue())["valid"] is False

    def test_unordered_either_element(self):
        h = H((0, "invoke", "enqueue", 1), (0, "ok", "enqueue", 1),
              (1, "invoke", "enqueue", 2), (1, "ok", "enqueue", 2),
              (2, "invoke", "dequeue", None), (2, "ok", "dequeue", 2),
              (3, "invoke", "dequeue", None), (3, "ok", "dequeue", 1))
        assert check_history_tpu(h, UnorderedQueue())["valid"] is True

    def test_double_dequeue_invalid(self):
        h = H((0, "invoke", "enqueue", 1), (0, "ok", "enqueue", 1),
              (1, "invoke", "dequeue", None), (1, "ok", "dequeue", 1),
              (2, "invoke", "dequeue", None), (2, "ok", "dequeue", 1))
        assert check_history_tpu(h, UnorderedQueue())["valid"] is False

    def test_crashed_enqueue_may_apply(self):
        h = H((0, "invoke", "enqueue", 5), (0, "info", "enqueue", 5),
              (1, "invoke", "dequeue", None), (1, "ok", "dequeue", 5))
        assert check_history_tpu(h, UnorderedQueue())["valid"] is True

    def test_random_golden_vs_object_search(self):
        rng = random.Random(9)
        decided = valid = invalid = 0
        for _ in range(150):
            h = random_queue_history(rng)
            want = check_model(h, UnorderedQueue())["valid"]
            got = check_history_tpu(h, UnorderedQueue(),
                                    capacity=512)["valid"]
            assert got is want or got is UNKNOWN, (want, got, list(h))
            decided += got is not UNKNOWN
            valid += want is True and got is True
            invalid += want is False and got is False
        assert decided > 100 and valid and invalid

    def test_count_field_overflow_falls_back(self):
        # one value pending >15 times simultaneously overflows even the
        # widest (4-bit) count field
        rows = []
        for _ in range(17):
            rows += [(0, "invoke", "enqueue", 9), (0, "ok", "enqueue", 9)]
        rows += [(1, "invoke", "dequeue", None), (1, "ok", "dequeue", 9)]
        h = H(*rows)
        assert check_history_tpu(h, UnorderedQueue()) is None
        assert linearizable(UnorderedQueue(), backend="tpu").check(
            {}, h)["valid"] is True

    def test_crashed_dequeue_stays_on_device_path(self):
        # a nil-value crashed dequeue can never be linearized under the
        # reference semantics (knossos steps it with the invocation's nil
        # value — model.clj:73-80), so pack_history drops it and the
        # drain history stays on the device path instead of silently
        # routing to the object search
        h = H((0, "invoke", "enqueue", 1), (0, "ok", "enqueue", 1),
              (1, "invoke", "dequeue", None), (1, "info", "dequeue", None))
        r = check_history_tpu(h, UnorderedQueue())
        assert r is not None and r["valid"] is True
        assert r["backend"] == "tpu"
        assert linearizable(UnorderedQueue(), backend="tpu").check(
            {}, h)["valid"] is True

    def test_crashed_dequeue_drop_matches_object_search(self):
        # differential: dropping nil crashed dequeues must not change any
        # verdict vs the object search that keeps (and never takes) them
        import random as _random
        from jepsen_tpu.checker.wgl import check_model
        n = 0
        for i in range(60):
            rng = _random.Random(900 + i)
            h = random_queue_history(rng, n_procs=3, n_ops=10, n_vals=3,
                                     crash_p=0.3)
            want = check_model(h, UnorderedQueue())["valid"]
            got = check_history_tpu(h, UnorderedQueue())
            if got is None or got["valid"] is UNKNOWN:
                continue
            n += 1
            assert got["valid"] is want, (i, got, want)
        assert n > 30

    def test_fifo_crashed_dequeue_stays_on_device_path(self):
        h = H((0, "invoke", "enqueue", 1), (0, "ok", "enqueue", 1),
              (1, "invoke", "dequeue", None), (1, "info", "dequeue", None),
              (2, "invoke", "dequeue", None), (2, "ok", "dequeue", 1))
        from jepsen_tpu.models import FIFOQueue as _FQ
        r = check_history_tpu(h, _FQ())
        assert r is not None and r["valid"] is True

    def test_fifo_crashed_dequeue_drop_matches_object_search(self):
        # random_fifo_history never crashes dequeues, so inject crashed
        # nil dequeues explicitly: the FIFO drop path must agree with the
        # object search (which keeps and never takes them) on every seed
        import random as _random
        from jepsen_tpu.checker.wgl import check_model
        from jepsen_tpu.history import History, Op
        from jepsen_tpu.models import FIFOQueue as _FQ
        n = 0
        for i in range(40):
            rng = _random.Random(1700 + i)
            base = list(random_fifo_history(rng, n_procs=3, n_ops=8))
            # a crashed nil dequeue at a random point mid-history (on a
            # fresh process id so pairing stays intact), plus one at the
            # end left forever-pending (no completion at all)
            cut = rng.randrange(len(base) + 1)
            t = base[cut - 1].time + 1 if cut else 0
            rows = (base[:cut]
                    + [Op(type="invoke", f="dequeue", value=None,
                          process=7, time=t),
                       Op(type="info", f="dequeue", value=None,
                          process=7, time=t + 1)]
                    + base[cut:])
            rows.append(Op(type="invoke", f="dequeue", value=None,
                           process=8, time=rows[-1].time + 1))
            h = History.of(rows)
            want = check_model(h, _FQ())["valid"]
            got = check_history_tpu(h, _FQ())
            if got is None or got["valid"] is UNKNOWN:
                continue
            n += 1
            assert got["valid"] is want, (i, got, want)
        assert n > 20

    def test_host_fallback_is_labeled(self):
        # count-field overflow routes to the object search; the result
        # must SAY so instead of reading as a device verdict
        rows = []
        for _ in range(17):
            rows += [(0, "invoke", "enqueue", 9), (0, "ok", "enqueue", 9)]
        rows += [(1, "invoke", "dequeue", None), (1, "ok", "dequeue", 9)]
        h = H(*rows)
        assert check_history_tpu(h, UnorderedQueue()) is None
        out = linearizable(UnorderedQueue(), backend="tpu").check({}, h)
        assert out["valid"] is True
        assert out["backend"] == "cpu"
        assert out["fallback-from"] == "tpu"
        assert "kernel" in out["fallback-reason"] \
            or "encoding" in out["fallback-reason"]

    def test_never_dequeued_values_are_sinks(self):
        # 17 enqueues of one never-dequeued value used to overflow the
        # count nibble and fall back; sink encoding (no op ever reads the
        # count) keeps it on the device path
        rows = []
        for i in range(17):
            rows += [(0, "invoke", "enqueue", 9), (0, "ok", "enqueue", 9)]
        h = H(*rows)
        r = check_history_tpu(h, UnorderedQueue())
        assert r is not None and r["valid"] is True
        assert r["backend"] == "tpu"


# wide_history now lives in jepsen_tpu.testing (shared
# with examples/bench); re-exported here for importers.


class TestWideShapes:
    """Positive coverage for the lifted window/crash caps (VERDICT r2 weak
    #2): multi-word masks (MW>1), multi-word crashed sets (MC>1), and the
    ~100-thread aerospike concurrency shape, each vs the CPU oracle."""

    def test_100_concurrency_needs_window_128(self):
        from jepsen_tpu.checker.tpu import _window_needed
        h = wide_history(100, 2, seed=5)
        p = pack_history(h, CAS_REGISTER_KERNEL)
        assert _window_needed(p) > 64          # only window=128 (MW=4) fits
        assert check_packed(p, CAS_REGISTER_KERNEL)["valid"] is True
        r = check_packed_tpu(p, CAS_REGISTER_KERNEL, capacity=4096,
                             window=128, expand=256)
        assert r["valid"] is True              # device decides, positively

    def test_100_concurrency_corrupted_never_verifies(self):
        h = wide_history(100, 2, seed=5, corrupt=True)
        p = pack_history(h, CAS_REGISTER_KERNEL)
        assert check_packed(p, CAS_REGISTER_KERNEL)["valid"] is False
        r = check_packed_tpu(p, CAS_REGISTER_KERNEL, capacity=4096,
                             window=128, expand=256)
        assert r["valid"] is not True

    def test_48_concurrency_window_64(self):
        from jepsen_tpu.checker.tpu import _window_needed
        h = wide_history(48, 2, seed=3)
        p = pack_history(h, CAS_REGISTER_KERNEL)
        need = _window_needed(p)
        assert 32 < need <= 64                 # exercises MW=2
        assert check_packed(p, CAS_REGISTER_KERNEL)["valid"] is True
        r = check_packed_tpu(p, CAS_REGISTER_KERNEL, capacity=2048,
                             window=64, expand=128)
        assert r["valid"] is True

    def test_over_32_crashed_ops(self):
        # > 32 crashed ops needs the two-word crashed mask (MC=2)
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(260, n_procs=6, n_vals=8, seed=3,
                                      crash_p=0.3)
        p = pack_history(h, CAS_REGISTER_KERNEL)
        assert p.n - p.n_required > 32
        assert check_packed(p, CAS_REGISTER_KERNEL)["valid"] is True
        r = check_packed_tpu(p, CAS_REGISTER_KERNEL)
        assert r["valid"] is True

    def test_rung_selection_matches_needed_window(self):
        from jepsen_tpu.checker.tpu import (
            MAX_WINDOW, WIDE_LADDER, _ladder_for, _window_needed)
        h = wide_history(100, 2, seed=5)
        p = pack_history(h, CAS_REGISTER_KERNEL)
        rungs = _ladder_for(_window_needed(p))
        # capacity escalates at exactly the window this history needs,
        # with the expansion-heavy wide rungs (slim best-first expansion
        # goes lossy long before a witness on wide frontiers)
        assert all(w >= _window_needed(p) for _, w, _ in rungs)
        assert rungs == tuple((c, 128, e) for c, e in WIDE_LADDER)
        # narrow histories escalate capacity at the narrow window only —
        # no multi-word-mask rungs for a history that can't use them
        assert all(w == 32 for _, w, _ in _ladder_for(5))
        # impossibly wide: refutation is impossible (window overflow is
        # inevitable), so the ladder is capped to the witness-hunting
        # rungs at MAX_WINDOW
        over = _ladder_for(4000)
        assert all(w == MAX_WINDOW for _, w, _ in over)
        assert over == tuple((c, MAX_WINDOW, e)
                             for c, e in WIDE_LADDER[:2])

    def test_first_rung_env_override(self, monkeypatch):
        # JTPU_FIRST_RUNG pins the measured winner per accelerator
        from jepsen_tpu.checker.tpu import _capacity_ladder
        monkeypatch.setenv("JTPU_FIRST_RUNG", "512,48")
        assert _capacity_ladder()[0] == (512, 48)
        monkeypatch.setenv("JTPU_FIRST_RUNG", "garbage")
        assert _capacity_ladder()[0][0] in (32, 128)  # default per backend
        # the override also drives real checks end-to-end
        monkeypatch.setenv("JTPU_FIRST_RUNG", "64,16")
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(200, n_procs=4, n_vals=8, seed=1)
        assert check_history_tpu(h, CASRegister())["valid"] is True


class TestMaskHelpers:
    """The multi-word mask primitives vs arbitrary-precision Python ints."""

    def _to_words(self, x, mw):
        return [(x >> (32 * w)) & 0xFFFFFFFF for w in range(mw)]

    def test_shr1_shrby_trailing_ones(self):
        import jax.numpy as jnp
        from jepsen_tpu.checker.tpu import (
            _shr1_multi, _shr_by_mw, _trailing_ones_mw)
        rng = random.Random(2)
        for mw in (1, 2, 4):
            ints = [rng.getrandbits(32 * mw) for _ in range(64)]
            m = jnp.asarray(
                np.array([self._to_words(x, mw) for x in ints],
                         dtype=np.uint32))
            got1 = np.asarray(_shr1_multi(m, mw))
            want1 = np.array([self._to_words(x >> 1, mw) for x in ints],
                             dtype=np.uint32)
            assert (got1 == want1).all()

            def t_ones(x):
                t = 0
                while x & 1:
                    x >>= 1
                    t += 1
                return t
            gott = np.asarray(_trailing_ones_mw(m, mw))
            wantt = np.array([min(t_ones(x), 32 * mw) for x in ints])
            assert (gott == wantt).all()

            ts = np.array([rng.randrange(0, 32 * mw + 1) for _ in ints],
                          dtype=np.int32)
            gots = np.asarray(_shr_by_mw(m, jnp.asarray(ts), mw))
            wants = np.array(
                [self._to_words(x >> int(t), mw)
                 for x, t in zip(ints, ts)], dtype=np.uint32)
            assert (gots == wants).all()


class TestReadonlyClosureRegression:
    """The pure-op closure must absorb only READ-ONLY ops. A write of the
    current value is NOT movable: this history needs it later as a
    state-restoring step (the minimal counterexample that broke an
    earlier state-unchanged-here closure rule)."""

    def test_rewrite_as_restoring_step(self):
        h = H((0, "invoke", "write", 0),
              (1, "invoke", "cas", (0, 1)),
              (2, "invoke", "write", 0),
              (2, "ok", "write", 0),
              (1, "ok", "cas", (0, 1)),
              (0, "ok", "write", 0),
              (2, "invoke", "read", None),
              (0, "invoke", "write", 1),
              (0, "ok", "write", 1),
              (2, "ok", "read", 0))
        p = pack_history(h, CAS_REGISTER_KERNEL)
        assert check_packed(p, CAS_REGISTER_KERNEL)["valid"] is True
        assert check_packed_tpu(p, CAS_REGISTER_KERNEL,
                                capacity=512)["valid"] is True

    def test_cas_same_value_is_readonly(self):
        from jepsen_tpu.models.core import F_CAS, F_READ, F_WRITE
        ro = CAS_REGISTER_KERNEL.readonly
        assert ro(F_READ, 3, -1) and ro(F_CAS, 2, 2)
        assert not ro(F_WRITE, 2, -1) and not ro(F_CAS, 2, 3)


def unique_queue_history(n_ops=200, n_procs=5, seed=1, corrupt=False):
    """Unique sequential enqueue values — the realistic disque/rabbitmq
    shape (reference disque.clj:305-310) that used to blow the 8-value
    kernel limit. Linearizable by construction (dequeues return a value
    whose enqueue completed; empty-queue dequeues fail) unless corrupt."""
    rng = random.Random(seed)
    h = History()
    free = list(range(n_procs))
    open_ops = {}
    pending = []
    nextv = done = t = 0
    while done < n_ops or open_ops:
        if free and done < n_ops and (not open_ops or rng.random() < 0.55):
            p = free.pop(rng.randrange(len(free)))
            if rng.random() < 0.55 or not pending:
                op = Op(type="invoke", f="enqueue", value=nextv, process=p,
                        time=t)
                nextv += 1
            else:
                op = Op(type="invoke", f="dequeue", value=None, process=p,
                        time=t)
            h.append(op)
            open_ops[p] = op
            done += 1
        else:
            p = rng.choice(list(open_ops))
            inv = open_ops.pop(p)
            if inv.f == "enqueue":
                pending.append(inv.value)
                h.append(Op(type="ok", f="enqueue", value=inv.value,
                            process=p, time=t))
            else:
                if pending:
                    v = pending.pop(rng.randrange(len(pending)))
                    h.append(Op(type="ok", f="dequeue", value=v,
                                process=p, time=t))
                else:
                    h.append(Op(type="fail", f="dequeue", value=None,
                                process=p, time=t))
            free.append(p)
        t += 1
    if corrupt:
        rows = list(h)
        for i in range(len(rows) - 1, -1, -1):
            if rows[i].type == "ok" and rows[i].f == "dequeue":
                rows[i] = rows[i].replace(value=10**7)
                break
        h = History.of(rows)
    return h


class TestQueueValueSymmetry:
    """Adaptive bit-field packing (interval value sharing + per-value
    count widths + never-dequeued sinks) keeps production-shaped queue
    histories on the device path (VERDICT r2 weak #4)."""

    def test_200_op_unique_values_ride_device_path(self):
        h = unique_queue_history(200, seed=1)
        r = check_history_tpu(h, UnorderedQueue())
        assert r is not None and r["valid"] is True
        assert r["backend"] == "tpu"

    def test_200_op_corrupted_detected_on_device(self):
        h = unique_queue_history(200, seed=1, corrupt=True)
        r = check_history_tpu(h, UnorderedQueue())
        assert r is not None and r["valid"] is False
        assert r["backend"] == "tpu"

    def test_unique_value_fuzz_vs_object_oracle(self):
        rng = random.Random(3)
        fallbacks = 0
        for seed in range(60):
            h = unique_queue_history(14, n_procs=3, seed=seed,
                                     corrupt=(seed % 3 == 0))
            want = check_model(h, UnorderedQueue())["valid"]
            r = check_history_tpu(h, UnorderedQueue(), capacity=512)
            if r is None:
                fallbacks += 1
                continue
            got = r["valid"]
            assert got is want or got is UNKNOWN, (seed, want, got)
        assert fallbacks == 0  # every unique-value history fits the word

    def test_interval_sharing_reuses_fields(self):
        # sequential lifetimes share one bit: 30 values, depth 1
        rows = []
        for v in range(30):
            rows += [(0, "invoke", "enqueue", v), (0, "ok", "enqueue", v),
                     (1, "invoke", "dequeue", None),
                     (1, "ok", "dequeue", v)]
        h = H(*rows)
        from jepsen_tpu.ops.encode import pack_with_init
        p, kernel = pack_with_init(h, UnorderedQueue())
        # all 30 values colored onto very few bit fields
        assert len(p.value_table) <= 2
        r = check_history_tpu(h, UnorderedQueue())
        assert r["valid"] is True and r["backend"] == "tpu"


def random_fifo_history(rng, n_procs=3, n_ops=10, corrupt_p=0.25,
                        crash_p=0.12):
    """Random concurrent FIFO history: unique enqueue values; dequeues
    usually pop the true head, sometimes an out-of-order or bogus value
    (often refutable), sometimes enqueues crash."""
    h = History()
    free = list(range(n_procs))
    open_ops = {}
    q = []
    nextv = done = t = 0
    while done < n_ops or open_ops:
        if free and done < n_ops and (not open_ops or rng.random() < 0.5):
            p = free.pop(rng.randrange(len(free)))
            if rng.random() < 0.55 or not q:
                op = Op(type="invoke", f="enqueue", value=nextv, process=p,
                        time=t)
                nextv += 1
            else:
                op = Op(type="invoke", f="dequeue", value=None, process=p,
                        time=t)
            h.append(op)
            open_ops[p] = op
            done += 1
        else:
            p = rng.choice(list(open_ops))
            inv = open_ops.pop(p)
            if inv.f == "enqueue":
                if rng.random() < crash_p:
                    h.append(Op(type="info", f="enqueue", value=inv.value,
                                process=p, time=t))
                    free.append(p)
                    t += 1
                    continue
                q.append(inv.value)
                h.append(Op(type="ok", f="enqueue", value=inv.value,
                            process=p, time=t))
            else:
                if q and rng.random() >= corrupt_p:
                    v = q.pop(0)
                elif q and rng.random() < 0.5:
                    v = q.pop(rng.randrange(len(q)))
                else:
                    v = 999
                h.append(Op(type="ok", f="dequeue", value=v, process=p,
                            time=t))
            free.append(p)
        t += 1
    return h


class TestFIFOQueueKernel:
    """The last model family gains a device kernel (VERDICT r2 missing
    #5): a 7-slot x 4-bit ring word with interval-colored value ids."""

    def test_strict_order_enforced(self):
        from jepsen_tpu.models import FIFOQueue
        ok = H((0, "invoke", "enqueue", "a"), (0, "ok", "enqueue", "a"),
               (0, "invoke", "enqueue", "b"), (0, "ok", "enqueue", "b"),
               (1, "invoke", "dequeue", None), (1, "ok", "dequeue", "a"),
               (1, "invoke", "dequeue", None), (1, "ok", "dequeue", "b"))
        r = check_history_tpu(ok, FIFOQueue())
        assert r["valid"] is True and r["backend"] == "tpu"
        # b before a violates FIFO order (an UnorderedQueue would accept)
        bad = H((0, "invoke", "enqueue", "a"), (0, "ok", "enqueue", "a"),
                (0, "invoke", "enqueue", "b"), (0, "ok", "enqueue", "b"),
                (1, "invoke", "dequeue", None), (1, "ok", "dequeue", "b"))
        assert check_history_tpu(bad, FIFOQueue())["valid"] is False

    def test_concurrent_enqueues_either_order(self):
        from jepsen_tpu.models import FIFOQueue
        h = H((0, "invoke", "enqueue", "a"),
              (1, "invoke", "enqueue", "b"),
              (0, "ok", "enqueue", "a"), (1, "ok", "enqueue", "b"),
              (2, "invoke", "dequeue", None), (2, "ok", "dequeue", "b"),
              (3, "invoke", "dequeue", None), (3, "ok", "dequeue", "a"))
        assert check_history_tpu(h, FIFOQueue())["valid"] is True

    def test_initial_queue_contents(self):
        from jepsen_tpu.models import FIFOQueue
        h = H((0, "invoke", "dequeue", None), (0, "ok", "dequeue", "x"))
        assert check_history_tpu(h, FIFOQueue(("x",)))["valid"] is True
        assert check_history_tpu(h, FIFOQueue(("y",)))["valid"] is False

    def test_depth_overflow_falls_back(self):
        from jepsen_tpu.models import FIFOQueue
        rows = []
        for v in range(9):   # 9 simultaneous pendings > 7 ring slots
            rows += [(0, "invoke", "enqueue", v), (0, "ok", "enqueue", v)]
        h = H(*rows)
        assert check_history_tpu(h, FIFOQueue()) is None
        assert linearizable(FIFOQueue(), backend="tpu").check(
            {}, h)["valid"] is True

    def test_id_reuse_across_disjoint_lifetimes(self):
        from jepsen_tpu.models import FIFOQueue
        # 40 sequential enqueue/dequeue pairs: 40 values share few ids
        rows = []
        for v in range(40):
            rows += [(0, "invoke", "enqueue", v), (0, "ok", "enqueue", v),
                     (1, "invoke", "dequeue", None),
                     (1, "ok", "dequeue", v)]
        h = H(*rows)
        r = check_history_tpu(h, FIFOQueue())
        assert r["valid"] is True and r["backend"] == "tpu"

    def test_random_fuzz_vs_object_oracle(self):
        from jepsen_tpu.checker.wgl import check_model
        from jepsen_tpu.models import FIFOQueue
        rng = random.Random(17)
        decided_t = decided_f = 0
        for i in range(80):
            h = random_fifo_history(rng)
            want = check_model(h, FIFOQueue())["valid"]
            r = check_history_tpu(h, FIFOQueue(), capacity=512)
            if r is None:
                continue    # over the ring bounds: legal fallback
            got = r["valid"]
            assert got is want or got is UNKNOWN, (i, want, got)
            decided_t += got is True
            decided_f += got is False
        assert decided_t > 10 and decided_f > 10


class TestForcedFastForward:
    """The forced fast-forward: frontiers whose op is the unique
    candidate (no concurrent required op, no linearizable crashed op)
    advance in-level instead of paying a sort-level each — staggered
    histories (the reference's 1/30-stagger tutorial shape, etcd.clj:172)
    collapse from ~n levels to ~#concurrent-regions."""

    def test_staggered_levels_collapse(self):
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(2000, n_procs=5, n_vals=8, seed=4,
                                      overlap_p=0.05)
        r = check_history_tpu(h, CASRegister())
        assert r["valid"] is True
        # without fast-forward this shape needs ~0.8*n levels
        assert r["levels"] < 2000 / 4, r["levels"]

    def test_staggered_differential_with_crashes_and_corruption(self):
        import random as _random
        from jepsen_tpu.checker.wgl import check_model
        from jepsen_tpu.testing import (corrupt_one_read,
                                        simulate_register_history)
        rng = _random.Random(5150)
        n = 0
        for i in range(120):
            hh = simulate_register_history(
                rng.randint(10, 50), n_procs=rng.randint(2, 5), n_vals=4,
                seed=rng.getrandbits(30),
                crash_p=rng.choice([0.0, 0.15]),
                overlap_p=rng.choice([0.02, 0.1, 0.4]))
            if rng.random() < 0.5:
                hh = corrupt_one_read(hh, rng)
            want = check_model(hh, CASRegister(),
                               max_configs=500_000)["valid"]
            got = check_history_tpu(hh, CASRegister())["valid"]
            if UNKNOWN in (want, got):
                continue
            n += 1
            assert got is want, (i, want, got)
        assert n > 80

    def test_refutation_mid_forced_run(self):
        # a stale read at a forced (non-concurrent) position: the
        # fast-forward must STOP at the failing frontier and the search
        # must refute with the prefix anchored there
        rows = []
        for v in range(6):
            rows.append(Op(type="invoke", f="write", value=v, process=0,
                           time=2 * v))
            rows.append(Op(type="ok", f="write", value=v, process=0,
                           time=2 * v + 1))
        rows.append(Op(type="invoke", f="read", value=None, process=1,
                       time=12))
        rows.append(Op(type="ok", f="read", value=77, process=1,
                       time=13))
        for v in range(6, 10):
            rows.append(Op(type="invoke", f="write", value=v, process=0,
                           time=2 * v + 2))
            rows.append(Op(type="ok", f="write", value=v, process=0,
                           time=2 * v + 3))
        r = check_history_tpu(History.of(rows), CASRegister())
        assert r["valid"] is False
        assert r["max-linearized-prefix"] == 6  # blocked at the read

    def test_forced_run_into_completion(self):
        # a fully sequential valid history: one forced run to the end
        rows = []
        for v in range(40):
            rows.append(Op(type="invoke", f="write", value=v % 4,
                           process=0, time=2 * v))
            rows.append(Op(type="ok", f="write", value=v % 4, process=0,
                           time=2 * v + 1))
        r = check_history_tpu(History.of(rows), CASRegister())
        assert r["valid"] is True
        assert r["levels"] <= 3, r["levels"]  # one fast-forwarded level


class TestScale:
    """North-star scale coverage (VERDICT r1: device path must be exercised
    beyond toy sizes in CI; the full 10k rung hides behind -m slow)."""

    def test_1k_valid_history_device_path(self):
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(1000, n_procs=5, n_vals=16, seed=42,
                                      crash_p=0.002)
        r = check_history_tpu(h, CASRegister())
        assert r["valid"] is True

    def test_1k_corrupted_history_detected(self):
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(1000, n_procs=5, n_vals=16, seed=42,
                                      crash_p=0.002)
        # corrupt one read completion to an impossible value
        rows = list(h)
        for i in range(len(rows) - 1, -1, -1):
            o = rows[i]
            if o.type == "ok" and o.f == "read" and o.value is not None:
                rows[i] = o.replace(value=(o.value + 1) % 16)
                break
        r = check_history_tpu(History.of(rows), CASRegister())
        # a corrupted read near the end is either refuted outright or
        # pushed past every rung (unknown); it must never verify
        assert r["valid"] is not True

    @pytest.mark.slow
    def test_10k_valid_history_device_path(self):
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(10_000, n_procs=5, n_vals=16, seed=42,
                                      crash_p=0.002)
        r = check_history_tpu(h, CASRegister())
        assert r["valid"] is True

    @pytest.mark.slow
    def test_width_100_device_decides_where_native_cannot_budget(self):
        # the width crossover (doc/native.md): at window ~100 the host
        # DFS explodes (native: 343s/83M configs unbounded on the build
        # host) while the pool search decides in ~6s on the CPU
        # backend — the device verdict must be definitive and correct,
        # and native within a 3M-config budget must still be searching
        from jepsen_tpu.checker.native import (available,
                                               check_history_native)
        from jepsen_tpu.testing import wide_history
        h = wide_history(100, 4, write_frac=0.2, seed=3)
        r = check_history_tpu(h, CASRegister())
        assert r["valid"] is True, r
        if available():
            rn = check_history_native(h, CASRegister(),
                                      max_configs=3_000_000)
            assert rn["valid"] is UNKNOWN, rn


class TestCrashWidth128:
    def test_90_crashed_ops_decided(self):
        # four crashed-mask words (MC=3 after bucketing): previously an
        # instant unknown past 64 crashed
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(400, n_procs=6, n_vals=8, seed=3,
                                      crash_p=0.35)
        p = pack_history(h, CAS_REGISTER_KERNEL)
        assert p.n - p.n_required > 64
        assert check_packed(p, CAS_REGISTER_KERNEL)["valid"] is True
        # the device search must at least never contradict; deciding this
        # crash-heavy shape can take the upper rungs, so allow unknown
        r = check_packed_tpu(p, CAS_REGISTER_KERNEL, capacity=2048,
                             window=32, expand=64)
        assert r["valid"] is not False

    @pytest.mark.slow
    def test_100k_op_history_device_path(self):
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(100_000, n_procs=5, n_vals=16,
                                      seed=4, crash_p=0.0002)
        r = check_history_tpu(h, CASRegister())
        assert r["valid"] is True
