"""TPU (batched JAX) linearizability backend tests.

Runs on the virtual 8-device CPU mesh (conftest.py). The CPU WGL from
jepsen_tpu.checker.wgl — itself validated against a brute-force oracle in
test_linearizable.py — is the reference semantics here.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker.tpu import (
    check_history_tpu, check_keyed_tpu, check_packed_tpu)
from jepsen_tpu.checker.wgl import check_packed, linearizable
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister, Mutex
from jepsen_tpu.models.core import CAS_REGISTER_KERNEL, MUTEX_KERNEL
from jepsen_tpu.ops import pack_history

from test_linearizable import H, random_register_history


class TestGoldenTPU:
    def test_sequential_valid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 0))
        assert check_history_tpu(h, CASRegister())["valid"] is True

    def test_sequential_invalid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        assert check_history_tpu(h, CASRegister())["valid"] is False

    def test_cas_then_stale_read_invalid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "cas", (0, 1)), (1, "ok", "cas", (0, 1)),
              (2, "invoke", "read", None), (2, "ok", "read", 0))
        assert check_history_tpu(h, CASRegister())["valid"] is False

    def test_crashed_write_may_apply(self):
        h = H((0, "invoke", "write", 1),
              (0, "info", "write", 1),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        assert check_history_tpu(h, CASRegister())["valid"] is True

    def test_crashed_write_applies_late(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "write", 9),
              (2, "invoke", "read", None), (2, "ok", "read", 0),
              (3, "invoke", "read", None), (3, "ok", "read", 9))
        assert check_history_tpu(h, CASRegister())["valid"] is True

    def test_mutex(self):
        bad = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None),
                (1, "invoke", "acquire", None), (1, "ok", "acquire", None))
        assert check_history_tpu(bad, Mutex())["valid"] is False
        good = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None),
                 (0, "invoke", "release", None), (0, "ok", "release", None),
                 (1, "invoke", "acquire", None), (1, "ok", "acquire", None))
        assert check_history_tpu(good, Mutex())["valid"] is True

    def test_empty(self):
        assert check_history_tpu(H(), CASRegister())["valid"] is True

    def test_nonnil_initial_value(self):
        h = H((0, "invoke", "read", None), (0, "ok", "read", 7))
        assert check_history_tpu(h, CASRegister(7))["valid"] is True
        assert check_history_tpu(h, CASRegister(8))["valid"] is False


class TestInitStates:
    def test_keyed_nonnil_initial_value(self):
        # regression: keyed path must honor the model instance's init state
        h = H((0, "invoke", "read", None), (0, "ok", "read", 7))
        out = check_keyed_tpu({0: h}, CASRegister(7))
        assert out["results"][0]["valid"] is True
        out8 = check_keyed_tpu({0: h}, CASRegister(8))
        assert out8["results"][0]["valid"] is False

    def test_locked_mutex_initial_state(self):
        # regression: Mutex(True) must start locked on the device path
        h = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None))
        assert check_history_tpu(h, Mutex(True))["valid"] is False
        assert check_history_tpu(h, Mutex(False))["valid"] is True

    def test_window_over_32_rejected(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0))
        with pytest.raises(ValueError):
            check_history_tpu(h, CASRegister(), window=64)


class TestAgainstCPUOracle:
    def test_random_histories_agree(self):
        rng = random.Random(7)
        mismatches = []
        for i in range(120):
            h = random_register_history(rng, n_procs=4, n_ops=8, n_vals=3)
            p = pack_history(h, CAS_REGISTER_KERNEL)
            want = check_packed(p, CAS_REGISTER_KERNEL)["valid"]
            got = check_packed_tpu(p, CAS_REGISTER_KERNEL,
                                   capacity=512)["valid"]
            if got is not want and got is not UNKNOWN:
                mismatches.append((i, want, got))
        assert not mismatches

    def test_longer_histories_agree(self):
        rng = random.Random(99)
        for _ in range(10):
            h = random_register_history(rng, n_procs=5, n_ops=60, n_vals=4,
                                        crash_p=0.05)
            p = pack_history(h, CAS_REGISTER_KERNEL)
            want = check_packed(p, CAS_REGISTER_KERNEL)["valid"]
            got = check_packed_tpu(p, CAS_REGISTER_KERNEL)["valid"]
            assert got is want or got is UNKNOWN

    def test_facade_tpu_backend(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        c = linearizable(CASRegister(), backend="tpu")
        assert c.check({}, h)["valid"] is False


class TestKeyedBatch:
    def _keyed(self, rng, n_keys):
        keyed = {}
        for k in range(n_keys):
            keyed[k] = random_register_history(
                rng, n_procs=3, n_ops=10, n_vals=3, crash_p=0.1)
        return keyed

    def test_keyed_matches_per_key_cpu(self):
        rng = random.Random(11)
        keyed = self._keyed(rng, 6)
        out = check_keyed_tpu(keyed, CASRegister(), capacity=512)
        for k, h in keyed.items():
            want = check_packed(pack_history(h, CAS_REGISTER_KERNEL),
                                CAS_REGISTER_KERNEL)["valid"]
            got = out["results"][k]["valid"]
            assert got is want or got is UNKNOWN, (k, want, got)

    def test_keyed_sharded_over_mesh(self):
        devs = jax.devices()
        assert len(devs) == 8, "conftest should force an 8-device CPU mesh"
        mesh = jax.sharding.Mesh(np.array(devs), ("keys",))
        rng = random.Random(13)
        keyed = self._keyed(rng, 16)
        out = check_keyed_tpu(keyed, CASRegister(), capacity=256, mesh=mesh)
        assert set(out["results"]) == set(keyed)
        for k, h in keyed.items():
            want = check_packed(pack_history(h, CAS_REGISTER_KERNEL),
                                CAS_REGISTER_KERNEL)["valid"]
            got = out["results"][k]["valid"]
            assert got is want or got is UNKNOWN, (k, want, got)

    def test_keyed_unsupported_op_isolated(self):
        # regression: one key with an un-encodable op must not abort the
        # batch — that key alone goes unknown
        good = H((0, "invoke", "write", 1), (0, "ok", "write", 1))
        bad = H((0, "invoke", "frobnicate", None),
                (0, "ok", "frobnicate", None))
        out = check_keyed_tpu({"g": good, "b": bad}, CASRegister())
        assert out["results"]["g"]["valid"] is True
        assert out["results"]["b"]["valid"] is UNKNOWN
        assert out["valid"] is UNKNOWN

    def test_keyed_unpadded_key_count(self):
        # key count not divisible by mesh size exercises the padding path
        devs = jax.devices()
        mesh = jax.sharding.Mesh(np.array(devs), ("keys",))
        rng = random.Random(17)
        keyed = self._keyed(rng, 5)
        out = check_keyed_tpu(keyed, CASRegister(), capacity=256, mesh=mesh)
        assert set(out["results"]) == set(keyed)
