"""Real-tool nemesis tests (local control mode): the command lines the
fault injectors emit run through the REAL coreutils/procps on this
host — the flag-drift class dummy transcripts cannot catch. Companion
of tests/test_net_real.py (tc) and tests/test_install_real.py
(wget/tar); the clock helpers' real-g++ compile lives in
tests/test_nemesis_time.py.
"""

import os
import shutil
import subprocess
import sys
import time

import pytest

from jepsen_tpu import nemesis
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op


@pytest.fixture
def test_map():
    t = {"nodes": ["localnode"], "ssh": {"mode": "local"}}
    yield t
    for s in t.get("_sessions", {}).values():
        s.close()


class TestTruncateFileReal:
    def test_drops_exactly_the_tail(self, test_map, tmp_path):
        f = tmp_path / "wal.log"
        f.write_bytes(b"A" * 1000)
        op = Op(type="info", f="truncate", process="nemesis",
                value={"localnode": {"file": str(f), "drop": 137}})
        nemesis.truncate_file().invoke(test_map, op)
        assert f.stat().st_size == 863
        assert f.read_bytes() == b"A" * 863

    def test_missing_file_is_tolerated(self, test_map, tmp_path):
        """-c must keep truncate from creating the file (the reference
        relies on this: truncating a log that rotated away is a no-op,
        nemesis.clj:274-300)."""
        ghost = tmp_path / "gone.log"
        op = Op(type="info", f="truncate", process="nemesis",
                value={"localnode": {"file": str(ghost), "drop": 10}})
        nemesis.truncate_file().invoke(test_map, op)
        assert not ghost.exists()


class TestGrepkillReal:
    def test_kills_only_matching_processes(self, test_map):
        marker = f"jepsen-victim-{os.getpid()}"
        victim = subprocess.Popen(
            [sys.executable, "-c",
             f"import time  # {marker}\ntime.sleep(300)"])
        bystander = subprocess.Popen(
            [sys.executable, "-c", "import time\ntime.sleep(10)"])
        try:
            cu.grepkill(test_map, "localnode", marker)
            deadline = time.time() + 5
            while victim.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            assert victim.poll() is not None, "victim survived grepkill"
            assert bystander.poll() is None, "bystander was killed"
        finally:
            for p in (victim, bystander):
                if p.poll() is None:
                    p.kill()
                p.wait()

    def test_no_match_is_quiet(self, test_map):
        cu.grepkill(test_map, "localnode",
                    "no-process-has-this-name-ever-xyzzy")


class TestEnsureUserReal:
    """ensure_user against the real debian adduser (root container):
    creation, idempotence ('already exists' tolerance), cleanup.
    Lives here (not in test_install_real.py) so the wget/tar module
    gate there cannot skip it — pytest marks accumulate across levels
    and cannot be overridden per-class."""

    USER = "jepsen-test-usr"

    @pytest.fixture(autouse=True)
    def _cleanup(self):
        yield
        import subprocess
        subprocess.run(["deluser", "--quiet", "--remove-home",
                        self.USER], capture_output=True)

    @pytest.mark.skipif(os.geteuid() != 0 or not shutil.which("adduser"),
                        reason="needs root + adduser")
    def test_creates_then_tolerates_existing(self, test_map):
        import pwd
        assert cu.ensure_user(test_map, "localnode", self.USER) \
            == self.USER
        assert pwd.getpwnam(self.USER).pw_name == self.USER
        # second call must hit the 'already exists' tolerance
        assert cu.ensure_user(test_map, "localnode", self.USER) \
            == self.USER
