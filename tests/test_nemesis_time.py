"""Clock-fault toolkit tests: native helpers compile and behave at the CLI
boundary; the nemesis drives the right remote commands (dummy control)."""

import os
import subprocess

import pytest

from jepsen_tpu import control
from jepsen_tpu.nemesis import time as nt
from jepsen_tpu.history import Op

from test_nemesis import dummy_test, logs, nop


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    """Compile both helpers locally with g++ (same compiler the control
    plane invokes on nodes)."""
    d = tmp_path_factory.mktemp("clock-helpers")
    bins = {}
    for bin_name, src in nt.HELPERS.items():
        out = str(d / bin_name)
        subprocess.run(
            ["g++", "-O2", "-o", out, os.path.join(nt.RESOURCE_DIR, src)],
            check=True, capture_output=True)
        bins[bin_name] = out
    return bins


class TestNativeHelpers:
    def test_bump_usage_exit_1(self, built):
        p = subprocess.run([built["bump-time"]], capture_output=True)
        assert p.returncode == 1
        assert b"usage" in p.stderr

    def test_strobe_usage_exit_1(self, built):
        p = subprocess.run([built["strobe-time"]], capture_output=True)
        assert p.returncode == 1
        assert b"delta" in p.stderr.lower()

    def test_bump_without_root_fails_cleanly(self, built):
        # bump by 0ms still calls settimeofday; as non-root it must exit 2
        # (reference exit-code contract), as root it exits 0 having set the
        # clock to itself.
        p = subprocess.run([built["bump-time"], "0"], capture_output=True)
        assert p.returncode in (0, 2)

    def test_strobe_zero_duration_exits_zero(self, built):
        # duration 0: loop never entered, clock restored once; as non-root
        # settimeofday fails with exit 2, as root prints 0 adjustments
        p = subprocess.run([built["strobe-time"], "100", "10", "0"],
                           capture_output=True)
        assert p.returncode in (0, 2)
        if p.returncode == 0:
            assert p.stdout.strip() == b"0"


class TestClockNemesis:
    def test_setup_installs_and_resets(self):
        test = dummy_test()
        with control.session_pool(test):
            nt.clock_nemesis().setup(test)
            for node in test["nodes"]:
                cmds = logs(test)[node]
                assert any("UPLOAD" in c and "bump-time.cc" in c
                           for c in cmds)
                assert any("g++ -O2 -o bump-time" in c for c in cmds)
                assert any("g++ -O2 -o strobe-time" in c for c in cmds)
                assert any("ntpdate" in c for c in cmds)

    def test_bump_targets_only_planned_nodes(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nt.clock_nemesis()
            n.invoke(test, nop("bump", value={"n2": 5000, "n4": -250.5}))
            cmds = logs(test)
            assert any("/opt/jepsen/bump-time 5000" in c
                       for c in cmds["n2"])
            assert any("bump-time" in c and "250.5" in c
                       for c in cmds["n4"])
            assert not any("bump-time" in c for c in cmds["n1"])

    def test_strobe_passes_all_three_args(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nt.clock_nemesis()
            n.invoke(test, nop("strobe", value={
                "n3": {"delta": 100, "period": 10, "duration": 2}}))
            assert any("/opt/jepsen/strobe-time 100 10 2" in c
                       for c in logs(test)["n3"])

    def test_reset_subset(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nt.clock_nemesis()
            n.invoke(test, nop("reset", value=["n1", "n5"]))
            cmds = logs(test)
            assert any("ntpdate" in c for c in cmds["n1"])
            assert not any("ntpdate" in c for c in cmds["n2"])

    def test_unknown_f_raises(self):
        test = dummy_test()
        with control.session_pool(test):
            with pytest.raises(ValueError):
                nt.clock_nemesis().invoke(test, nop("warp"))


class TestFaketime:
    def test_script_shape(self):
        from jepsen_tpu import faketime
        s = faketime.script("/usr/bin/db", -30, 5)
        assert s.startswith("#!/bin/bash")
        assert 'faketime -m -f "-30s x5.0" /usr/bin/db "$@"' in s
        s2 = faketime.script("/usr/bin/db", 10, 0.5)
        assert '"+10s x0.5"' in s2

    def test_wrap_idempotent_under_dummy(self):
        # dummy sessions answer rc=0 to the existence probe, exercising the
        # already-wrapped path: wrapper rewritten, no mv
        from jepsen_tpu import faketime
        test = dummy_test()
        with control.session_pool(test):
            faketime.wrap(test, "n1", "/opt/db/bin", 0, 2)
            cmds = logs(test)["n1"]
            assert not any(c.startswith("mv ") for c in cmds)
            assert any("chmod a+x /opt/db/bin" in c for c in cmds)
            assert any("faketime" in c and ">" in c for c in cmds)


class TestGenerators:
    def test_reset_gen_shape(self):
        test = {"nodes": ["a", "b", "c"]}
        op = nt.reset_gen(test, 0)
        assert op["f"] == "reset"
        assert set(op["value"]) <= {"a", "b", "c"}
        assert len(op["value"]) >= 1

    def test_bump_gen_ranges(self):
        test = {"nodes": ["a", "b", "c", "d", "e"]}
        for _ in range(50):
            op = nt.bump_gen(test, 0)
            for node, delta in op["value"].items():
                assert 4 <= abs(delta) <= 2 ** 18

    def test_strobe_gen_ranges(self):
        test = {"nodes": ["a", "b"]}
        for _ in range(50):
            op = nt.strobe_gen(test, 0)
            for node, spec in op["value"].items():
                assert 4 <= spec["delta"] <= 2 ** 18
                assert 1 <= spec["period"] <= 2 ** 10
                assert 0 <= spec["duration"] <= 32

    def test_clock_gen_mixes(self):
        g = nt.clock_gen()
        test = {"nodes": ["a", "b"], "concurrency": 2}
        fs = {g.op(test, 0).f for _ in range(60)}
        assert fs == {"reset", "bump", "strobe"}