"""Linearizability checker tests: golden histories + a brute-force oracle.

The brute-force oracle enumerates every permutation of the paired ops that
respects real-time order and asks whether any is a legal sequential run —
exponential but exact, used to validate WGL on small random histories.
"""

import itertools
import random

from jepsen_tpu.checker.wgl import (
    check_model, check_packed, linearizable)
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister, Mutex, FIFOQueue
from jepsen_tpu.models.core import CAS_REGISTER_KERNEL, MUTEX_KERNEL
from jepsen_tpu.models.core import is_inconsistent
from jepsen_tpu.ops import pack_history, RET_INF


def H(*rows):
    return History.of([
        Op(type=t, f=f, value=v, process=p, time=i)
        for i, (p, t, f, v) in enumerate(rows)
    ])


# ---------------------------------------------------------------------------
# Brute-force oracle
# ---------------------------------------------------------------------------

def _pairs(history):
    pending = {}
    rows = []
    for ev, o in enumerate(history):
        if o.is_invoke:
            pending[o.process] = (ev, o)
        elif o.process in pending:
            inv_ev, inv_op = pending.pop(o.process)
            if o.is_fail:
                continue
            val = o.value if (o.is_ok and o.value is not None) else inv_op.value
            rows.append((inv_ev, ev if o.is_ok else 10**9,
                         inv_op.replace(value=val), o.is_ok))
    for inv_ev, inv_op in pending.values():
        rows.append((inv_ev, 10**9, inv_op, False))
    return rows


def brute_force_linearizable(history, model):
    rows = _pairs(history)
    required = [i for i, r in enumerate(rows) if r[3]]
    optional = [i for i, r in enumerate(rows) if not r[3]]
    n = len(rows)
    # try all subsets of optional (crashed) ops, all permutations
    for r in range(len(optional) + 1):
        for subset in itertools.combinations(optional, r):
            chosen = sorted(required + list(subset))
            for perm in itertools.permutations(chosen):
                # real-time order: if ret[a] < inv[b], a must precede b
                ok = True
                for idx_a in range(len(perm)):
                    for idx_b in range(idx_a + 1, len(perm)):
                        a, b = perm[idx_a], perm[idx_b]
                        if rows[b][1] < rows[a][0]:
                            ok = False
                            break
                    if not ok:
                        break
                if not ok:
                    continue
                m = model
                good = True
                for i in perm:
                    m = m.step(rows[i][2])
                    if is_inconsistent(m):
                        good = False
                        break
                if good:
                    return True
    return False


# ---------------------------------------------------------------------------
# Golden histories
# ---------------------------------------------------------------------------

class TestGolden:
    def test_empty_valid(self):
        assert check_model(H(), CASRegister())["valid"] is True

    def test_sequential_valid(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 0))
        assert check_model(h, CASRegister())["valid"] is True

    def test_sequential_invalid_read(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        assert check_model(h, CASRegister())["valid"] is False

    def test_concurrent_write_read_valid(self):
        # read overlaps write; may see it
        h = H((0, "invoke", "write", 1),
              (1, "invoke", "read", None),
              (0, "ok", "write", 1),
              (1, "ok", "read", 1))
        assert check_model(h, CASRegister())["valid"] is True

    def test_read_after_cas_invalid(self):
        # w0 completes; cas 0->1 completes; read 0 strictly after -> invalid
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "cas", (0, 1)), (1, "ok", "cas", (0, 1)),
              (2, "invoke", "read", None), (2, "ok", "read", 0))
        assert check_model(h, CASRegister())["valid"] is False

    def test_crashed_write_may_apply(self):
        h = H((0, "invoke", "write", 1),
              (0, "info", "write", 1),
              (1, "invoke", "read", None), (1, "ok", "read", 1))
        assert check_model(h, CASRegister())["valid"] is True

    def test_crashed_write_may_not_apply(self):
        h = H((0, "invoke", "write", 1),
              (0, "info", "write", 1),
              (1, "invoke", "read", None), (1, "ok", "read", None))
        # read nil is don't-care; trivially fine
        assert check_model(h, CASRegister())["valid"] is True

    def test_crashed_write_applies_late(self):
        # crashed write may linearize AFTER the read of the old value
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "write", 9),
              (2, "invoke", "read", None), (2, "ok", "read", 0),
              (3, "invoke", "read", None), (3, "ok", "read", 9))
        assert check_model(h, CASRegister())["valid"] is True

    def test_double_acquire_invalid(self):
        h = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None),
              (1, "invoke", "acquire", None), (1, "ok", "acquire", None))
        assert check_model(h, Mutex())["valid"] is False

    def test_mutex_valid(self):
        h = H((0, "invoke", "acquire", None), (0, "ok", "acquire", None),
              (0, "invoke", "release", None), (0, "ok", "release", None),
              (1, "invoke", "acquire", None), (1, "ok", "acquire", None))
        assert check_model(h, Mutex())["valid"] is True

    def test_fifo_queue(self):
        h = H((0, "invoke", "enqueue", "a"), (0, "ok", "enqueue", "a"),
              (1, "invoke", "enqueue", "b"), (1, "ok", "enqueue", "b"),
              (0, "invoke", "dequeue", None), (0, "ok", "dequeue", "a"))
        assert check_model(h, FIFOQueue())["valid"] is True
        h2 = H((0, "invoke", "enqueue", "a"), (0, "ok", "enqueue", "a"),
               (1, "invoke", "enqueue", "b"), (1, "ok", "enqueue", "b"),
               (0, "invoke", "dequeue", None), (0, "ok", "dequeue", "b"))
        assert check_model(h2, FIFOQueue())["valid"] is False


class TestPackedAgreesWithModel:
    def test_packed_golden(self):
        cases = [
            H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 0)),
            H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 1)),
            H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "cas", (0, 1)), (1, "ok", "cas", (0, 1)),
              (2, "invoke", "read", None), (2, "ok", "read", 0)),
        ]
        for h in cases:
            got = check_packed(pack_history(h, CAS_REGISTER_KERNEL),
                               CAS_REGISTER_KERNEL)["valid"]
            want = check_model(h, CASRegister())["valid"]
            assert got == want


def random_register_history(rng, n_procs=3, n_ops=5, n_vals=3,
                            crash_p=0.2):
    """Generate a random concurrent register history."""
    h = History()
    free = list(range(n_procs))
    open_ops = {}
    ops_left = n_ops
    t = 0
    while (ops_left > 0 and (free or open_ops)) or open_ops:
        # choose to invoke or complete
        if free and ops_left > 0 and (not open_ops or rng.random() < 0.5):
            p = rng.choice(free)
            free.remove(p)
            ops_left -= 1
            f = rng.choice(["read", "write", "cas"])
            if f == "read":
                v = None
            elif f == "write":
                v = rng.randrange(n_vals)
            else:
                v = (rng.randrange(n_vals), rng.randrange(n_vals))
            op = Op(type="invoke", f=f, value=v, process=p, time=t)
            h.append(op)
            open_ops[p] = op
        else:
            p = rng.choice(list(open_ops))
            inv = open_ops.pop(p)
            r = rng.random()
            if r < crash_p:
                h.append(Op(type="info", f=inv.f, value=inv.value,
                            process=p, time=t))
                # crashed process never returns; don't free it
            else:
                val = inv.value
                if inv.f == "read":
                    val = rng.randrange(n_vals) if rng.random() < 0.9 else None
                typ = "ok" if r < 0.9 else "fail"
                h.append(Op(type=typ, f=inv.f, value=val, process=p, time=t))
                free.append(p)
        t += 1
    return h


class TestAgainstBruteForce:
    def test_random_histories(self):
        rng = random.Random(42)
        n_checked = 0
        n_valid = 0
        for _ in range(300):
            h = random_register_history(rng)
            want = brute_force_linearizable(h, CASRegister())
            got_model = check_model(h, CASRegister())["valid"]
            got_packed = check_packed(
                pack_history(h, CAS_REGISTER_KERNEL),
                CAS_REGISTER_KERNEL)["valid"]
            assert got_model == want, f"check_model wrong on:\n{h.to_jsonl()}"
            assert got_packed == want, f"check_packed wrong on:\n{h.to_jsonl()}"
            n_checked += 1
            n_valid += bool(want)
        # sanity: the generator produces a healthy mix
        assert 20 < n_valid < 280, (n_valid, n_checked)


class TestCheckerFacade:
    def test_linearizable_checker(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0),
              (1, "invoke", "read", None), (1, "ok", "read", 0))
        c = linearizable(CASRegister())
        assert c.check({}, h)["valid"] is True

    def test_model_from_test_map(self):
        h = H((0, "invoke", "write", 0), (0, "ok", "write", 0))
        c = linearizable()
        assert c.check({"model": CASRegister()}, h)["valid"] is True
