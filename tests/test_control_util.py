"""Remote sysadmin helpers + debian OS prep, driven through the dummy
control plane with scripted responses."""

import pytest

from jepsen_tpu import control
from jepsen_tpu.control import util as cu
from jepsen_tpu.os import debian


def dummy_test(responses=None, **over):
    test = {
        "nodes": ["n1", "n2"],
        "concurrency": 2,
        "ssh": {"mode": "dummy", "dummy-responses": responses or {}},
    }
    test.update(over)
    return test


def log_of(test, node="n1"):
    return list(test["_sessions"][node].log)


class TestExistsWgetArchive:
    def test_exists_true_false(self):
        t = dummy_test({"stat /there": "ok",
                        "stat /missing": (1, "", "no such file")})
        with control.session_pool(t):
            assert cu.exists(t, "n1", "/there") is True
            assert cu.exists(t, "n1", "/missing") is False

    def test_wget_skips_when_present(self):
        t = dummy_test({"stat": "ok"})
        with control.session_pool(t):
            name = cu.wget(t, "n1", "https://example.com/db-1.2.tgz")
            assert name == "db-1.2.tgz"
            assert not any("wget" in c for c in log_of(t))

    def test_wget_downloads_when_missing(self):
        t = dummy_test({"stat": (1, "", "nope")})
        with control.session_pool(t):
            cu.wget(t, "n1", "https://example.com/db-1.2.tgz")
            assert any("wget --tries 20" in c and "db-1.2.tgz" in c
                       for c in log_of(t))

    def test_install_archive_single_root_collapses(self):
        t = dummy_test({"stat": (1, "", "nope"),
                        "ls -A": "db-1.2-amd64",
                        "dirname": "/opt"})
        with control.session_pool(t):
            dest = cu.install_archive(
                t, "n1", "https://example.com/db-1.2.tgz", "/opt/db")
            assert dest == "/opt/db"
            cmds = log_of(t)
            assert any(c.startswith("cd /tmp/jepsen/") and "tar xf" in c
                       for c in cmds)
            assert any("mv /tmp/jepsen/" in c and c.endswith("/opt/db")
                       and "db-1.2-amd64" in c for c in cmds)
            assert any("rm -rf /opt/db" in c for c in cmds)

    def test_install_archive_zip(self):
        t = dummy_test({"stat": (1, "", "nope"),
                        "ls -A": "a\nb",
                        "dirname": "/opt"})
        with control.session_pool(t):
            cu.install_archive(t, "n1", "file:///tmp/x.zip", "/opt/db")
            cmds = log_of(t)
            assert any("unzip /tmp/x.zip" in c for c in cmds)
            # multiple roots: whole tmpdir moves to dest
            assert any("mv /tmp/jepsen/" in c and c.endswith("/opt/db")
                       for c in cmds)
            assert not any("wget" in c for c in cmds)


class TestDaemons:
    def test_start_daemon_command_shape(self):
        t = dummy_test()
        with control.session_pool(t):
            cu.start_daemon(t, "n1", "/opt/etcd/etcd",
                            "--name", "n1", "--data-dir", "/var/lib/etcd",
                            logfile="/var/log/etcd.log",
                            pidfile="/var/run/etcd.pid",
                            chdir="/opt/etcd")
            cmds = log_of(t)
            assert any("Jepsen starting" in c and ">> /var/log/etcd.log" in c
                       for c in cmds)
            start = next(c for c in cmds if "start-stop-daemon" in c)
            for frag in ("--start", "--background", "--no-close",
                         "--make-pidfile", "--exec /opt/etcd/etcd",
                         "--pidfile /var/run/etcd.pid", "--chdir /opt/etcd",
                         "--oknodo", "--startas /opt/etcd/etcd",
                         "-- --name n1 --data-dir /var/lib/etcd",
                         ">> /var/log/etcd.log 2>&1"):
                assert frag in start, (frag, start)

    def test_stop_daemon_by_pidfile(self):
        t = dummy_test({"cat /var/run/db.pid": "1234"})
        with control.session_pool(t):
            cu.stop_daemon(t, "n1", "/var/run/db.pid")
            cmds = log_of(t)
            assert any("kill -9 1234" in c for c in cmds)
            assert any("rm -rf /var/run/db.pid" in c for c in cmds)

    def test_stop_daemon_by_cmd(self):
        t = dummy_test()
        with control.session_pool(t):
            cu.stop_daemon(t, "n1", "/var/run/db.pid", cmd="etcd")
            assert any("killall -9 -w etcd" in c for c in log_of(t))

    def test_grepkill(self):
        t = dummy_test()
        with control.session_pool(t):
            cu.grepkill(t, "n1", "cockroach")
            assert any("ps auxww | grep cockroach" in c
                       and "xargs kill -9" in c for c in log_of(t))
        t2 = dummy_test()
        with control.session_pool(t2):
            cu.grepkill(t2, "n1", "java", signal=15)
            assert any("kill -15" in c for c in log_of(t2))


class TestEnsureUser:
    def test_creates(self):
        t = dummy_test()
        with control.session_pool(t):
            assert cu.ensure_user(t, "n1", "etcd") == "etcd"
            assert any("adduser --disabled-password" in c
                       for c in log_of(t))

    def test_tolerates_existing(self):
        t = dummy_test({"adduser": (1, "", "user etcd already exists")})
        with control.session_pool(t):
            assert cu.ensure_user(t, "n1", "etcd") == "etcd"


class TestDebian:
    def test_install_only_missing(self):
        t = dummy_test({"dpkg --get-selections":
                        "wget\tinstall\ncurl\tinstall"})
        with control.session_pool(t):
            debian.install(t, "n1", ["wget", "curl", "ntpdate"])
            cmds = log_of(t)
            inst = [c for c in cmds if "apt-get install" in c]
            assert len(inst) == 1
            assert "ntpdate" in inst[0]
            assert "curl" not in inst[0]

    def test_install_all_present_is_noop(self):
        t = dummy_test({"dpkg --get-selections": "wget\tinstall"})
        with control.session_pool(t):
            debian.install(t, "n1", ["wget"])
            assert not any("apt-get install" in c for c in log_of(t))

    def test_version_pinning(self):
        t = dummy_test({"apt-cache policy": "Installed: 1.0\n"})
        with control.session_pool(t):
            debian.install(t, "n1", {"db": "2.0"})
            assert any("apt-get install -y --force-yes db=2.0" in c
                       for c in log_of(t))
            log_of(t).clear()
        t2 = dummy_test({"apt-cache policy": "Installed: 2.0\n"})
        with control.session_pool(t2):
            debian.install(t2, "n1", {"db": "2.0"})
            assert not any("apt-get install" in c for c in log_of(t2))

    def test_setup_hostfile_rewrites(self):
        t = dummy_test({"cat /etc/hosts":
                        "127.0.0.1\tweird-name\n10.0.0.2 n2"})
        with control.session_pool(t):
            debian.setup_hostfile(t, "n1")
            assert any("127.0.0.1\tlocalhost" in c and "/etc/hosts" in c
                       for c in log_of(t))

    def test_setup_hostfile_noop_when_fine(self):
        t = dummy_test({"cat /etc/hosts": "127.0.0.1\tlocalhost"})
        with control.session_pool(t):
            debian.setup_hostfile(t, "n1")
            assert not any("> /etc/hosts" in c for c in log_of(t))

    def test_os_setup_runs(self):
        t = dummy_test({"cat /etc/hosts": "127.0.0.1\tlocalhost",
                        "date +%s": "1000000000",
                        "stat -c": "999999999"})
        with control.session_pool(t):
            debian.os().setup(t, "n1")
            cmds = log_of(t)
            assert any("apt-get install" in c for c in cmds)

    def test_add_repo_idempotent(self):
        t = dummy_test({"stat": "ok"})  # list file exists
        with control.session_pool(t):
            debian.add_repo(t, "n1", "webupd8", "deb http://x y main")
            assert not any("sources.list.d" in c and "echo" in c
                           for c in log_of(t))