"""Generator semantics tests — mirrors reference generator_test.clj's
in-memory op-pump: fake worker threads pull ops until exhaustion."""

import threading
import time
from collections import defaultdict

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.history import NEMESIS, Op
from jepsen_tpu.util import with_relative_time


def pump(g, concurrency=2, with_nemesis=False, max_ops=10_000):
    """Spin worker threads pulling ops until the generator is exhausted.
    Returns {thread: [op, ...]} (generator_test.clj:10-25)."""
    test = {"concurrency": concurrency, "nodes": ["n1", "n2", "n3"]}
    out = defaultdict(list)
    lock = threading.Lock()
    threads = list(range(concurrency)) + ([NEMESIS] if with_nemesis else [])

    def worker(t):
        with gen.threads_bound(gen.all_threads(test) if with_nemesis
                               else frozenset(range(concurrency))):
            n = 0
            while n < max_ops:
                o = gen.op_and_validate(g, test, t)
                if o is None:
                    break
                with lock:
                    out[t].append(o)
                n += 1

    ts = [threading.Thread(target=worker, args=(t,)) for t in threads]
    with with_relative_time():
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
            assert not t.is_alive(), "worker deadlocked"
    return dict(out)


def ops_of(result):
    return [o for ops in result.values() for o in ops]


class TestCoercions:
    def test_none_is_void(self):
        assert gen.gen(None).op({}, 0) is None

    def test_dict_is_infinite(self):
        g = gen.gen({"f": "read"})
        o1 = g.op({}, 0)
        o2 = g.op({}, 0)
        assert o1.f == "read" and o2.f == "read" and o1 is not o2
        assert o1.type == "invoke"

    def test_fn_gen(self):
        g = gen.gen(lambda test, process: Op(type="invoke", f="x",
                                             value=process))
        assert g.op({}, 7).value == 7

    def test_list_is_seq(self):
        g = gen.gen([gen.once({"f": "a"}), gen.once({"f": "b"})])
        assert g.op({}, 0).f == "a"
        assert g.op({}, 0).f == "b"
        assert g.op({}, 0) is None


class TestLimit:
    def test_limit_total(self):
        res = pump(gen.limit(10, {"f": "read"}), concurrency=4)
        assert len(ops_of(res)) == 10

    def test_once(self):
        res = pump(gen.once({"f": "read"}), concurrency=4)
        assert len(ops_of(res)) == 1


class TestSeq:
    # generator_test.clj seq semantics: generators exhausted in order
    def test_seq_in_order(self):
        g = gen.seq([gen.limit(2, {"f": "a"}), gen.limit(3, {"f": "b"})])
        res = pump(g, concurrency=1)
        assert [o.f for o in res[0]] == ["a", "a", "b", "b", "b"]

    def test_concat(self):
        g = gen.concat(gen.once({"f": "a"}), gen.once({"f": "b"}))
        res = pump(g, concurrency=2)
        assert sorted(o.f for o in ops_of(res)) == ["a", "b"]


class TestMix:
    def test_mix_draws_from_all(self):
        g = gen.limit(200, gen.mix([{"f": "a"}, {"f": "b"}]))
        fs = {o.f for o in ops_of(pump(g, concurrency=2))}
        assert fs == {"a", "b"}


class TestTimeLimit:
    def test_time_limit_stops(self):
        g = gen.time_limit(0.2, {"f": "read"})
        t0 = time.monotonic()
        res = pump(gen.delay(0.01, g), concurrency=2)
        dt = time.monotonic() - t0
        assert ops_of(res)  # got some ops
        assert dt < 5


class TestRouting:
    def test_nemesis_routing(self):
        # generator_test.clj:34-95 nemesis routing: nemesis sees its gen,
        # clients see theirs
        g = gen.nemesis(gen.limit(3, {"f": "break"}),
                        gen.limit(5, {"f": "read"}))
        res = pump(g, concurrency=2, with_nemesis=True)
        assert all(o.f == "break" for o in res.get(NEMESIS, []))
        assert len(res.get(NEMESIS, [])) == 3
        client_ops = [o for t, ops in res.items() if t != NEMESIS
                      for o in ops]
        assert all(o.f == "read" for o in client_ops)
        assert len(client_ops) == 5

    def test_clients_hides_nemesis(self):
        g = gen.clients(gen.limit(4, {"f": "read"}))
        res = pump(g, concurrency=2, with_nemesis=True)
        assert not res.get(NEMESIS)
        assert len(ops_of(res)) == 4

    def test_on_filters_threads(self):
        g = gen.on_threads(lambda t: t == 0, gen.limit(3, {"f": "x"}))
        res = pump(g, concurrency=3)
        assert len(res.get(0, [])) == 3
        assert not res.get(1) and not res.get(2)


class TestReserve:
    def test_reserve_ranges(self):
        g = gen.reserve(2, gen.limit(10, {"f": "writes"}),
                        gen.limit(10, {"f": "reads"}))
        res = pump(g, concurrency=5)
        for t, ops in res.items():
            if t in (0, 1):
                assert all(o.f == "writes" for o in ops)
            else:
                assert all(o.f == "reads" for o in ops)

    def test_reserve_requires_default(self):
        with pytest.raises(ValueError):
            gen.reserve(2, {"f": "a"})


class TestSynchronize:
    def test_synchronize_releases_together(self):
        order = []
        lock = threading.Lock()

        def record(test, process):
            with lock:
                order.append(("op", time.monotonic()))
            return None

        g = gen.seq([
            gen.on_threads(lambda t: t == 0, gen.Sleep(0.2)),
            gen.synchronize(gen.limit(2, {"f": "after"})),
        ])
        res = pump(g, concurrency=2)
        assert len(ops_of(res)) == 2

    def test_phases(self):
        # generator_test.clj phases: all threads finish phase 1 before 2
        g = gen.phases(gen.limit(2, {"f": "p1"}),
                       gen.limit(2, {"f": "p2"}))
        res = pump(g, concurrency=2)
        fs = [o.f for o in ops_of(res)]
        assert sorted(fs) == ["p1", "p1", "p2", "p2"]

    def test_then(self):
        g = gen.then_(gen.once({"f": "second"}), gen.once({"f": "first"}))
        res = pump(g, concurrency=2)
        fs = [o.f for o in ops_of(res)]
        assert sorted(fs) == ["first", "second"]


class TestEach:
    def test_each_thread_gets_own_copy(self):
        g = gen.each(lambda: gen.limit(2, {"f": "mine"}))
        res = pump(g, concurrency=3)
        assert all(len(ops) == 2 for ops in res.values())
        assert len(res) == 3


class TestFilter:
    def test_filter(self):
        src = gen.seq([gen.once({"f": "a"}), gen.once({"f": "b"}),
                       gen.once({"f": "a"})])
        g = gen.filter_gen(lambda o: o.f == "a", src)
        res = pump(g, concurrency=1)
        assert [o.f for o in res[0]] == ["a", "a"]


class TestWorkloads:
    def test_cas_gen_shapes(self):
        g = gen.limit(100, gen.cas_gen())
        for o in ops_of(pump(g, concurrency=2)):
            assert o.f in ("read", "write", "cas")
            if o.f == "cas":
                assert len(o.value) == 2
            if o.f == "read":
                assert o.value is None

    def test_queue_gen_unique_enqueues(self):
        g = gen.limit(100, gen.queue_gen())
        vals = [o.value for o in ops_of(pump(g, concurrency=3))
                if o.f == "enqueue"]
        assert len(vals) == len(set(vals))

    def test_start_stop(self):
        g = gen.limit(4, gen.start_stop(0, 0))
        res = pump(g, concurrency=1)
        assert [o.f for o in res[0]] == ["start", "stop", "start", "stop"]


class TestDelayTil:
    def test_delay_til_aligns(self):
        g = gen.delay_til(0.05, gen.limit(4, {"f": "x"}))
        res = pump(g, concurrency=2)
        assert len(ops_of(res)) == 4


class TestValidation:
    def test_rejects_completion_types(self):
        g = gen.gen({"type": "ok", "f": "read"})
        with pytest.raises(ValueError):
            gen.op_and_validate(g, {"concurrency": 1}, 0)

    def test_process_to_thread(self):
        test = {"concurrency": 3}
        assert gen.process_to_thread(0, test) == 0
        assert gen.process_to_thread(5, test) == 2
        assert gen.process_to_thread(NEMESIS, test) == NEMESIS

    def test_process_to_node(self):
        test = {"nodes": ["n1", "n2"]}
        assert gen.process_to_node(0, test) == "n1"
        assert gen.process_to_node(3, test) == "n2"
