"""Check-daemon + engine tests (the `serve` marker, doc/serve.md).

Covers the explicit executable Engine (warm-cache accounting: a second
check in the same shape bucket pays ZERO cold compiles), the CRC'd
request WAL and restart replay, admission control (bounded queue /
tenant quota / footprint budget → 429 + Retry-After), fair per-tenant
dequeue, the per-bucket circuit breaker (trip, half-open probe, close),
per-request deadlines (:info/timeout), graceful drain, the HTTP API
end-to-end, and the JTPU_SERVE kill-switch identity contract.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import serve as serve_ns
from jepsen_tpu.checker import tpu as T
from jepsen_tpu.checker.engine import Engine, default_engine
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.ops.encode import pack_with_init

pytestmark = pytest.mark.serve


def _ops(n_pairs=2, value=1):
    """A small valid register history as raw op dicts (what tenants
    POST)."""
    rows = []
    t = 0
    for i in range(n_pairs):
        rows.append({"type": "invoke", "f": "write", "value": value + i,
                     "process": 0, "time": t})
        rows.append({"type": "ok", "f": "write", "value": value + i,
                     "process": 0, "time": t + 1})
        rows.append({"type": "invoke", "f": "read", "value": None,
                     "process": 1, "time": t + 2})
        rows.append({"type": "ok", "f": "read", "value": value + i,
                     "process": 1, "time": t + 3})
        t += 4
    return rows


def _packed(ops=None):
    return pack_with_init(History.of(ops or _ops()), CASRegister())


def _daemon(tmp_path, start=False, **cfg):
    cfg.setdefault("root", str(tmp_path / "serve"))
    cfg.setdefault("backend", "tpu")
    d = serve_ns.CheckDaemon(serve_ns.ServeConfig(**cfg))
    if start:
        d.start()
    return d


def _wait_done(daemon, rid, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = daemon.status(rid)
        if doc and doc["state"] == "done":
            return doc
        time.sleep(0.02)
    raise AssertionError(f"request {rid} never finished: "
                         f"{daemon.status(rid)}")


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class TestEngine:
    def test_factories_route_through_engine_and_memoize(self):
        p, kernel = _packed()
        kid = T._kernel_key(kernel)
        f1 = T._jit_segment(kid, 32, 32, 4, 1)
        f2 = T._jit_segment(kid, 32, 32, 4, 1)
        assert f1 is f2  # the explicit table, same contract as lru_cache
        eng = default_engine()
        assert eng.builds >= 1 and eng.hits >= 1

    def test_lru_eviction_bounds_the_table(self):
        p, kernel = _packed()
        kid = T._kernel_key(kernel)
        eng = Engine("evict-test", max_entries=2)
        for cap in (8, 16, 32):
            eng.jit_single(kid, cap, 32, 4, 1)
        assert len(eng._fns) == 2
        assert eng.builds == 3

    def test_bucket_key_groups_shapes(self):
        p1, kernel = _packed(_ops(2))
        p2, _ = _packed(_ops(2, value=7))     # same shape, other values
        p3, _ = _packed(_ops(40))             # bigger required bucket
        assert Engine.bucket_key(p1, kernel) == \
            Engine.bucket_key(p2, kernel)
        assert Engine.bucket_key(p1, kernel) != \
            Engine.bucket_key(p3, kernel)

    def test_warm_then_same_bucket_checks_pay_zero_cold(self):
        """The warm-path satellite + acceptance proof: after Engine.warm
        a check in the bucket performs no cold compile (cold counter
        delta 0) and accounts as cache hits; a SECOND history in the
        same bucket rides the same executables."""
        from jepsen_tpu.resilience import supervised_check_packed
        eng = default_engine()
        p1, kernel = _packed(_ops(3))
        p2, _ = _packed(_ops(3, value=5))
        assert eng.bucket_key(p1, kernel) == eng.bucket_key(p2, kernel)
        eng.warm(p1, kernel)
        before = T.compile_snapshot()
        r1 = supervised_check_packed(p1, kernel)
        d1 = T.compile_delta(before)
        assert r1["valid"] is True
        assert d1["cold"] == 0, f"warm bucket cold-compiled: {d1}"
        assert d1["cache-hits"] >= 1
        mid = T.compile_snapshot()
        r2 = supervised_check_packed(p2, kernel)
        d2 = T.compile_delta(mid)
        assert r2["valid"] is True
        assert d2["cold"] == 0, f"second same-bucket check went cold: {d2}"
        assert d2["cache-hits"] >= 1

    def test_warm_is_idempotent_per_bucket(self):
        eng = default_engine()
        p, kernel = _packed(_ops(3))
        first = eng.warm(p, kernel)
        again = eng.warm(p, kernel)
        assert again["already-warm"] is True
        assert eng.warm_info(eng.bucket_key(p, kernel)) is not None
        assert first["shapes"] >= 1 or first["already-warm"]

    def test_enable_persistent_cache_best_effort(self, tmp_path):
        from jepsen_tpu.checker import engine as engine_mod
        out = engine_mod.enable_persistent_cache(str(tmp_path / "xc"))
        assert out in (None, str(tmp_path / "xc"))


# ---------------------------------------------------------------------------
# Request journal
# ---------------------------------------------------------------------------


class TestRequestJournal:
    def test_replay_returns_only_unfinished(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        j = serve_ns.RequestJournal(path)
        j.append({"event": "accepted", "id": "a", "history": _ops()})
        j.append({"event": "accepted", "id": "b", "history": _ops()})
        j.append({"event": "done", "id": "a", "valid": "True"})
        j.close()
        pending, stats = serve_ns.RequestJournal.replay(path)
        assert [r["id"] for r in pending] == ["b"]
        assert stats["records"] == 3 and stats["corrupt"] == 0

    def test_torn_tail_tolerated(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        j = serve_ns.RequestJournal(path)
        j.append({"event": "accepted", "id": "a", "history": _ops()})
        j.close()
        with open(path, "ab") as f:
            f.write(b"deadbeef {torn-mid-wri")  # no newline: torn tail
        pending, stats = serve_ns.RequestJournal.replay(path)
        assert [r["id"] for r in pending] == ["a"]
        assert stats["torn"] == 1

    def test_dropped_records_are_terminal(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        j = serve_ns.RequestJournal(path)
        j.append({"event": "accepted", "id": "a", "history": _ops()})
        j.append({"event": "dropped", "id": "a", "reason": "malformed"})
        j.close()
        pending, _ = serve_ns.RequestJournal.replay(path)
        assert pending == []


# ---------------------------------------------------------------------------
# Admission control + backpressure (no workers: requests stay queued)
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_bounded_queue_429_with_retry_after(self, tmp_path):
        d = _daemon(tmp_path, queue_max=2, tenant_max=10)
        for _ in range(2):
            code, _, _ = d.submit({"model": "cas-register",
                                   "history": _ops()})
            assert code == 202
        code, body, hdrs = d.submit({"model": "cas-register",
                                     "history": _ops()})
        assert code == 429
        assert body["error"] == "queue-full"
        assert int(hdrs["Retry-After"]) >= 1
        d.stop()

    def test_tenant_quota_protects_other_tenants(self, tmp_path):
        d = _daemon(tmp_path, queue_max=10, tenant_max=1)
        code, _, _ = d.submit({"tenant": "greedy",
                               "model": "cas-register",
                               "history": _ops()})
        assert code == 202
        code, body, hdrs = d.submit({"tenant": "greedy",
                                     "model": "cas-register",
                                     "history": _ops()})
        assert code == 429 and body["error"] == "tenant-quota"
        assert "Retry-After" in hdrs
        code, _, _ = d.submit({"tenant": "modest",
                               "model": "cas-register",
                               "history": _ops()})
        assert code == 202  # the quota is per tenant, not global
        d.stop()

    def test_footprint_budget_rejects_past_admission_bytes(self, tmp_path):
        d = _daemon(tmp_path, queue_max=10, bytes_budget=512)
        code, body, hdrs = d.submit({"model": "cas-register",
                                     "history": _ops()})
        assert code == 429 and body["error"] == "footprint"
        assert body["predicted-bytes"] > 512 == body["budget-bytes"]
        assert "Retry-After" in hdrs
        d.stop()

    def test_malformed_history_400_with_rule_ids(self, tmp_path):
        d = _daemon(tmp_path)
        bad = [{"type": "invoke", "f": "write", "value": 1,
                "process": 0, "time": 0},
               {"type": "invoke", "f": "write", "value": 2,
                "process": 0, "time": 1}]  # process reuse
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": bad})
        assert code == 400 and body["error"] == "malformed"
        assert body.get("lint")
        d.stop()

    def test_unknown_model_and_empty_history_400(self, tmp_path):
        d = _daemon(tmp_path)
        assert d.submit({"model": "nope", "history": _ops()})[0] == 400
        assert d.submit({"model": "cas-register",
                         "history": []})[0] == 400
        d.stop()

    def test_draining_503(self, tmp_path):
        d = _daemon(tmp_path)
        d.draining = True
        code, body, hdrs = d.submit({"model": "cas-register",
                                     "history": _ops()})
        assert code == 503 and body["error"] == "draining"
        assert "Retry-After" in hdrs
        d.stop()


class TestFairDequeue:
    def test_round_robin_across_tenants(self, tmp_path):
        d = _daemon(tmp_path, queue_max=32, tenant_max=32)
        for i in range(3):
            d.submit({"tenant": "t1", "model": "cas-register",
                      "history": _ops(value=i + 1)})
        d.submit({"tenant": "t2", "model": "cas-register",
                  "history": _ops(value=9)})
        order = [d._dequeue().tenant for _ in range(4)]
        # one t2 request interleaves within the first two slots instead
        # of waiting behind all of t1's backlog
        assert "t2" in order[:2], order
        assert sorted(order) == ["t1", "t1", "t1", "t2"]
        d.stop()


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    BUCKET = ("cas-register", 16, 0, 32)

    def test_trip_halfopen_probe_close(self):
        import random as _random
        from jepsen_tpu.resilience import OOM
        br = serve_ns.CircuitBreaker(2, 0.05, rng=_random.Random(3))
        ok, _, probe = br.allow(self.BUCKET)
        assert ok and not probe
        br.record(self.BUCKET, OOM, probe=False)
        br.record(self.BUCKET, OOM, probe=False)
        ok, retry, _ = br.allow(self.BUCKET)
        assert not ok and retry > 0
        time.sleep(0.08)
        ok, _, probe = br.allow(self.BUCKET)
        assert ok and probe            # half-open: exactly one probe
        ok2, _, _ = br.allow(self.BUCKET)
        assert not ok2                 # second concurrent probe refused
        br.record(self.BUCKET, None, probe=True)
        ok, _, probe = br.allow(self.BUCKET)
        assert ok and not probe        # closed again

    def test_probe_failure_doubles_cooldown(self):
        import random as _random
        from jepsen_tpu.resilience import WEDGE
        br = serve_ns.CircuitBreaker(1, 0.05, rng=_random.Random(5))
        br.record(self.BUCKET, WEDGE, probe=False)
        time.sleep(0.08)
        ok, _, probe = br.allow(self.BUCKET)
        assert ok and probe
        br.record(self.BUCKET, WEDGE, probe=True)
        snap = br.snapshot()
        rec = list(snap.values())[0]
        assert rec["state"] == "open"
        assert rec["cooldown-s"] == pytest.approx(0.1)

    def test_invalid_verdicts_do_not_trip(self):
        br = serve_ns.CircuitBreaker(1, 0.05)
        br.record(self.BUCKET, None, probe=False)   # clean check
        ok, _, _ = br.allow(self.BUCKET)
        assert ok

    def test_daemon_breaker_rejects_then_probes(self, tmp_path,
                                                monkeypatch):
        d = _daemon(tmp_path, start=True, queue_max=16,
                    breaker_fails=2, breaker_cooldown_s=0.1)
        monkeypatch.setattr(
            serve_ns.CheckDaemon, "_check",
            lambda self, req: {"valid": "unknown",
                               "error": "RESOURCE_EXHAUSTED (fake)",
                               "error-class": "oom"})
        for _ in range(2):
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops()})
            assert code == 202
            _wait_done(d, body["id"])
        code, body, hdrs = d.submit({"model": "cas-register",
                                     "history": _ops()})
        assert code == 503 and body["error"] == "breaker-open"
        assert "Retry-After" in hdrs
        time.sleep(0.2)                # past the jittered cooldown
        monkeypatch.setattr(serve_ns.CheckDaemon, "_check",
                            lambda self, req: {"valid": True})
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops()})
        assert code == 202             # the half-open probe
        _wait_done(d, body["id"])
        code, _, _ = d.submit({"model": "cas-register",
                               "history": _ops()})
        assert code == 202             # probe success closed the breaker
        d.stop()


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_overrun_returns_info_timeout(self, tmp_path, monkeypatch):
        d = _daemon(tmp_path, start=True, deadline_s=0.15)

        def slow(self, req):
            time.sleep(1.5)
            return {"valid": True}

        monkeypatch.setattr(serve_ns.CheckDaemon, "_check", slow)
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops()})
        assert code == 202
        doc = _wait_done(d, body["id"])
        assert doc["result"]["valid"] == "unknown"
        assert doc["result"]["error"] == ":info/timeout"
        assert doc["result"]["serve"]["timed-out"] is True
        assert d.stats["timeouts"] == 1
        d.stop()

    def test_per_request_deadline_overrides_default(self, tmp_path,
                                                    monkeypatch):
        d = _daemon(tmp_path, start=True, deadline_s=None)
        monkeypatch.setattr(
            serve_ns.CheckDaemon, "_check",
            lambda self, req: (time.sleep(0.5), {"valid": True})[1])
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops(), "deadline-s": 0.1})
        assert code == 202
        doc = _wait_done(d, body["id"])
        assert doc["result"]["error"] == ":info/timeout"
        d.stop()


# ---------------------------------------------------------------------------
# Crash safety: journal replay re-runs unfinished work, verdicts match
# the offline path
# ---------------------------------------------------------------------------


class TestCrashReplay:
    def test_killed_daemon_replays_and_matches_offline(self, tmp_path):
        # incarnation 1: accepts (journals) two requests but is "killed"
        # before any worker ran them
        d1 = _daemon(tmp_path, queue_max=8)
        for v in (1, 5):
            code, _, _ = d1.submit({"tenant": "t", "model":
                                    "cas-register",
                                    "history": _ops(value=v)})
            assert code == 202
        d1.journal.close()             # SIGKILL: nothing else persisted

        # incarnation 2 replays the WAL and finishes the work
        d2 = _daemon(tmp_path, start=True, queue_max=8)
        assert d2.replay_stats["requeued"] == 2
        assert d2.stats["replayed"] == 2
        with d2._lock:
            rids = list(d2._by_id)
        docs = [_wait_done(d2, rid) for rid in rids]
        d2.stop()

        # verdicts identical to the offline analyze path
        from jepsen_tpu.checker import check_safe
        from jepsen_tpu.checker.wgl import linearizable
        for doc, v in zip(sorted(docs, key=lambda x: x["id"]), (1, 5)):
            offline = check_safe(
                linearizable(CASRegister(), backend="tpu"),
                {"name": "offline"}, History.of(_ops(value=v)))
            assert doc["result"]["valid"] is offline["valid"] is True

    def test_drain_finishes_inflight_leaves_queued_journaled(
            self, tmp_path, monkeypatch):
        """The drain contract: in-flight work completes, queued work is
        NOT started — it stays journaled for the next incarnation."""
        d = _daemon(tmp_path, start=True)
        running = threading.Event()

        def slowish(self, req):
            running.set()
            time.sleep(0.4)
            return {"valid": True}

        monkeypatch.setattr(serve_ns.CheckDaemon, "_check", slowish)
        code, b1, _ = d.submit({"model": "cas-register",
                                "history": _ops()})
        assert code == 202
        assert running.wait(timeout=5)      # b1 is in flight
        code, b2, _ = d.submit({"model": "cas-register",
                                "history": _ops(value=7)})
        assert code == 202                  # b2 queued behind it
        out = d.drain(timeout_s=10)
        assert out["drained"] is True
        assert d.status(b1["id"])["state"] == "done"
        assert d.status(b2["id"])["state"] == "queued"
        d.stop()
        pending, _ = serve_ns.RequestJournal.replay(d.journal.path)
        assert [r["id"] for r in pending] == [b2["id"]]

    def test_finished_requests_are_not_replayed(self, tmp_path):
        d1 = _daemon(tmp_path, start=True)
        code, body, _ = d1.submit({"model": "cas-register",
                                   "history": _ops()})
        assert code == 202
        _wait_done(d1, body["id"])
        d1.stop()
        d2 = _daemon(tmp_path)
        pending, _ = serve_ns.RequestJournal.replay(d2.journal.path)
        assert pending == []
        d2.stop()


# ---------------------------------------------------------------------------
# HTTP API end-to-end
# ---------------------------------------------------------------------------


def _post(port, path, doc):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode() if doc is not None else b"",
        method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


class TestHTTP:
    def test_check_poll_healthz_drain(self, tmp_path):
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu")
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0,
            store_root=str(tmp_path / "store"))
        port = server.server_port
        try:
            code, body, _ = _post(port, "/check",
                                  {"tenant": "http", "model":
                                   "cas-register", "history": _ops()})
            assert code == 202 and body["state"] == "queued"
            rid = body["id"]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                code, doc = _get(port, f"/check/{rid}")
                if doc["state"] == "done":
                    break
                time.sleep(0.05)
            assert doc["state"] == "done"
            assert doc["result"]["valid"] is True
            # the result also persisted as a file
            assert os.path.exists(os.path.join(cfg.root, f"{rid}.json"))

            code, health = _get(port, "/healthz")
            assert code == 200 and health["ok"] is True
            assert health["stats"]["completed"] >= 1
            assert health["engine"]["warm-buckets"]

            code, doc = _get(port, "/check/nope")
            assert code == 404

            code, drained, _ = _post(port, "/drain", None)
            assert code == 200 and drained["drained"] is True
            assert daemon.drained.wait(timeout=5)
        finally:
            server.shutdown()
            daemon.stop()

    def test_saturated_queue_http_429_retry_after(self, tmp_path):
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   queue_max=0)
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0,
            store_root=str(tmp_path / "store"))
        try:
            code, body, hdrs = _post(
                server.server_port, "/check",
                {"model": "cas-register", "history": _ops()})
            assert code == 429 and body["error"] == "queue-full"
            assert int(hdrs["Retry-After"]) >= 1
        finally:
            server.shutdown()
            daemon.stop()

    def test_bad_json_400_and_results_browser_still_mounted(self,
                                                            tmp_path):
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"))
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0,
            store_root=str(tmp_path / "store"))
        port = server.server_port
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/check", data=b"{not json",
                method="POST")
            try:
                with urllib.request.urlopen(req) as r:
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 400
            # the grown handler still serves the results browser + the
            # Prometheus exposition (one port, one scrape target)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert "jtpu_serve_queue_depth" in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/") as r:
                assert r.status == 200
        finally:
            server.shutdown()
            daemon.stop()


# ---------------------------------------------------------------------------
# Observability surfacing
# ---------------------------------------------------------------------------


class TestServeObservability:
    def test_heartbeat_feeds_watch_and_live(self, tmp_path):
        from jepsen_tpu.obs import observatory
        d = _daemon(tmp_path, start=True)
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops()})
        assert code == 202
        _wait_done(d, body["id"])
        d._publish(force=True)
        p = observatory.read_progress(d.config.root)
        assert p is not None and p["serve"]["completed"] >= 1
        line = observatory.format_status(p)
        assert line.startswith("# serve: ")
        assert "queue" in line and "done" in line
        d.stop()


# ---------------------------------------------------------------------------
# Kill switch: daemon unused == identical behavior
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_env_alone_changes_no_verdicts(self, monkeypatch):
        p, kernel = _packed(_ops(3))
        monkeypatch.delenv("JTPU_SERVE", raising=False)
        r_off = T.check_packed_tpu(p, kernel)
        monkeypatch.setenv("JTPU_SERVE", "1")
        r_on = T.check_packed_tpu(p, kernel)
        for key in ("valid", "levels", "rung", "work", "crash-width"):
            assert r_off.get(key) == r_on.get(key)

    def test_plain_serve_handler_has_no_daemon_routes(self):
        from jepsen_tpu import web
        server = web.serve(host="127.0.0.1", port=0, root="store")
        try:
            handler = server.RequestHandlerClass
            assert not hasattr(handler, "daemon")
            assert "do_POST" not in dir(web.Handler) or \
                not hasattr(web.Handler, "do_POST")
        finally:
            server.server_close()

    def test_serve_cli_defaults_keep_daemon_off(self, monkeypatch):
        from jepsen_tpu import cli
        monkeypatch.delenv("JTPU_SERVE", raising=False)
        spec = cli.serve_cmd()["serve"]
        ns = spec["parser"]().parse_args([])
        assert ns.check_daemon is False
        assert serve_ns.serve_enabled() is False

    def test_importing_serve_leaves_checks_identical(self):
        import jepsen_tpu.serve  # noqa: F401 — the import IS the test
        p, kernel = _packed(_ops(3))
        r1 = T.check_packed_tpu(p, kernel)
        r2 = T.check_packed_tpu(p, kernel)
        for key in ("valid", "levels", "rung", "work"):
            assert r1.get(key) == r2.get(key)


# ---------------------------------------------------------------------------
# Gang-scheduled concurrent batching (doc/serve.md, "Concurrent
# batching"): coalescing, serial equivalence, poison bisection, the
# JTPU_SERVE_BATCH kill switch
# ---------------------------------------------------------------------------

#: keys on which a gang verdict must be indistinguishable from serial.
_VERDICT_KEYS = ("valid", "levels", "max-linearized-prefix",
                 "final-states", "frontier-op")


def _conc_ops(n, seed, value_base=0):
    """A CONCURRENT register history (4 procs, interleaved invokes) —
    deep enough that a segment_iters=1 gang needs several barriers,
    which the deadline-cancel test relies on."""
    import random as _random
    rng = _random.Random(seed)
    ops, t, pend, val = [], 0, {}, value_base
    for _ in range(n):
        p = rng.choice((0, 1, 2, 3))
        if p in pend:
            inv = pend.pop(p)
            ops.append({"process": p, "type": "ok", "f": inv["f"],
                        "value": inv["value"], "time": t})
        else:
            f = rng.choice(("write", "read"))
            v = val if f == "write" else None
            if f == "write":
                val += 1
            inv = {"process": p, "type": "invoke", "f": f, "value": v,
                   "time": t}
            ops.append(inv)
            pend[p] = inv
        t += 1
    for p, inv in pend.items():
        ops.append({"process": p, "type": "ok", "f": inv["f"],
                    "value": inv["value"], "time": t})
        t += 1
    return ops


def _offline(ops):
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    return check_safe(linearizable(CASRegister(), backend="tpu"),
                      {"name": "gang-offline"}, History.of(ops))


@pytest.fixture
def gang_fault():
    """Install/clear the checker.tpu gang fault seam."""
    def install(fn):
        T._GANG_FAULT = fn
    yield install
    T._GANG_FAULT = None


class TestCheckPackedGang:
    def test_gang_verdicts_match_serial(self):
        """The tentpole equivalence leg: one vmapped gang call renders
        per-member verdicts identical to serial check_packed_tpu."""
        histories = [_ops(3), _ops(5, value=9), _conc_ops(24, 3),
                     _ops(6, value=40)]
        pks, kernel = [], None
        for ops in histories:
            p, kernel = _packed(ops)
            pks.append(p)
        gang = T.check_packed_gang(pks, kernel)
        assert len(gang) == len(pks)
        for g, p in zip(gang, pks):
            serial = T.check_packed_tpu(p, kernel)
            for key in _VERDICT_KEYS:
                assert g.get(key) == serial.get(key), (key, g, serial)
            assert g["gang-size"] == len(pks)

    def test_empty_and_trivial_members(self):
        p, kernel = _packed(_ops(3))
        assert T.check_packed_gang([], kernel) == []

    def test_deadline_cancels_lane_not_cohort(self):
        """A member whose deadline passes is cancelled at the next
        segment barrier (:info/timeout, gang-cancelled) while its
        cohort finishes with serial-identical verdicts."""
        victim = _conc_ops(24, 5)
        cohort = _conc_ops(24, 6, value_base=100)
        pks, kernel = [], None
        for ops in (victim, cohort):
            p, kernel = _packed(ops)
            pks.append(p)
        out = T.check_packed_gang(
            pks, kernel, deadlines=[time.monotonic() - 1.0, None],
            segment_iters=1)
        from jepsen_tpu.checker import UNKNOWN
        assert out[0]["valid"] is UNKNOWN
        assert out[0]["error"] == ":info/timeout"
        assert out[0]["gang-cancelled"] is True
        serial = T.check_packed_tpu(pks[1], kernel)
        for key in _VERDICT_KEYS:
            assert out[1].get(key) == serial.get(key)

    def test_gang_fault_seam_raises_through(self, gang_fault):
        p, kernel = _packed(_ops(3))

        def boom(pks):
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")

        gang_fault(boom)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            T.check_packed_gang([p], kernel)


class TestBisectPoison:
    def test_isolates_single_poison(self):
        from jepsen_tpu.resilience import bisect_poison
        calls = []

        def run_gang(span):
            calls.append(list(span))
            if 3 in span:
                raise RuntimeError("RESOURCE_EXHAUSTED: poison")
            return [{"valid": True, "member": m} for m in span]

        results, poison, bisections = bisect_poison(
            list(range(6)), run_gang)
        assert poison == [3]
        assert bisections >= 1
        assert results[3]["error-class"] == "oom"
        for i in (0, 1, 2, 4, 5):
            assert results[i] == {"valid": True, "member": i}
        # the poison was isolated by splitting, not by 6 serial runs
        assert calls[0] == [0, 1, 2, 3, 4, 5]

    def test_all_clean_no_bisection(self):
        from jepsen_tpu.resilience import bisect_poison
        results, poison, bisections = bisect_poison(
            [10, 11], lambda span: [{"valid": True}] * len(span))
        assert poison == [] and bisections == 0
        assert all(r == {"valid": True} for r in results)

    def test_result_failure_class_drives_split(self):
        """A run_gang returning a single failure DICT (not raising)
        bisects too — the resilience result taxonomy is the trigger."""
        from jepsen_tpu.resilience import bisect_poison

        def run_gang(span):
            if 1 in span:
                return {"valid": "unknown", "error": "wedged",
                        "error-class": "wedge"}
            return [{"valid": True}] * len(span)

        results, poison, _ = bisect_poison([0, 1], run_gang)
        assert poison == [1]
        assert results[0] == {"valid": True}
        assert results[1]["error-class"] == "wedge"


class TestGangServe:
    def test_burst_coalesces_and_matches_offline(self, tmp_path):
        """4 same-bucket requests journaled by a killed incarnation
        re-queue together on restart — the worker's first dequeue leads
        a deterministic gang of 4, and every verdict matches the
        offline analyze path."""
        histories = [_ops(3), _ops(4, value=9), _ops(5, value=20),
                     _conc_ops(24, 7)]
        d1 = _daemon(tmp_path)
        for i, ops in enumerate(histories):
            code, _, _ = d1.submit({"tenant": f"t{i % 2}",
                                    "model": "cas-register",
                                    "history": ops})
            assert code == 202
        d1.journal.close()          # SIGKILL before any worker ran

        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=200.0)
        assert d2.batcher is not None
        assert d2.replay_stats["requeued"] == 4
        with d2._lock:
            rids = list(d2._by_id)
        docs = {rid: _wait_done(d2, rid) for rid in rids}
        assert d2.stats["batches"] >= 1
        assert d2.stats["max-batch"] >= 2
        d2.stop()
        by_order = sorted(docs.values(), key=lambda x: x["id"])
        pending, _ = serve_ns.RequestJournal.replay(d2.journal.path)
        assert pending == []        # every gang member reached done
        for doc in by_order:
            gang = doc["result"]["serve"]["gang"]
            assert gang["size"] >= 2 and gang["poison"] is False
        # order-insensitive equality against offline (ids regenerate)
        served = sorted(repr(d["result"]["valid"]) for d in by_order)
        offline = sorted(repr(_offline(o)["valid"]) for o in histories)
        assert served == offline

    def test_gang_wal_records_membership(self, tmp_path):
        d1 = _daemon(tmp_path)
        for v in (1, 5):
            d1.submit({"model": "cas-register",
                       "history": _ops(3, value=v)})
        d1.journal.close()
        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=200.0)
        with d2._lock:
            rids = list(d2._by_id)
        for rid in rids:
            _wait_done(d2, rid)
        d2.stop()
        gang_events, done_gangs = [], []
        from jepsen_tpu import journal as journal_ns
        records, _ = journal_ns.read_json_records(d2.journal.path)
        for rec in records:
            if rec.get("event") == "gang":
                gang_events.append(rec)
            if rec.get("event") == "done" and rec.get("gang"):
                done_gangs.append(rec)
        assert gang_events and sorted(gang_events[0]["ids"]) == \
            sorted(rids)
        assert done_gangs and all(
            sorted(rec["gang"]) == sorted(rids) for rec in done_gangs)

    def test_poison_member_isolated_breaker_counts_one(
            self, tmp_path, gang_fault):
        """The fault-isolation acceptance: one poison member OOMs any
        gang containing it; bisection fails ONLY it, survivors' verdicts
        match offline, and the bucket's breaker counts exactly 1."""
        survivors = [_ops(3), _ops(4, value=9), _ops(5, value=20)]
        poison = _ops(7, value=50)   # same bucket, unique row count
        poison_n = _packed(poison)[0].n
        assert all(_packed(o)[0].n != poison_n for o in survivors)

        def fault(pks):
            if any(p.n == poison_n for p in pks):
                raise RuntimeError("RESOURCE_EXHAUSTED: injected gang "
                                   "OOM")

        gang_fault(fault)
        d1 = _daemon(tmp_path)
        rid_p = d1.submit({"tenant": "a", "model": "cas-register",
                           "history": poison})[1]["id"]
        rid_s = [d1.submit({"tenant": "ab"[i % 2],
                            "model": "cas-register", "history": o}
                           )[1]["id"] for i, o in enumerate(survivors)]
        d1.journal.close()

        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=200.0, breaker_fails=5)
        with d2._lock:
            # replay regenerates nothing: ids persist through the WAL
            assert set(d2._by_id) == {rid_p, *rid_s}
        doc_p = _wait_done(d2, rid_p)
        docs_s = [_wait_done(d2, r) for r in rid_s]

        res = doc_p["result"]
        assert res["serve"]["gang"]["poison"] is True
        assert res["error-class"] == "oom"
        assert res["serve"]["gang"]["size"] == 4
        assert d2.stats["poisoned"] == 1
        assert d2.stats["bisections"] >= 1
        for doc, ops in zip(docs_s, survivors):
            r = doc["result"]
            assert r["serve"]["gang"]["poison"] is False
            offline = _offline(ops)
            for key in _VERDICT_KEYS:
                assert r.get(key) == offline.get(key), (key, r)
        snap = d2.breaker.snapshot()
        fails = [r["fails"] for r in snap.values()]
        assert fails == [1], snap    # exactly the poison, nothing else
        d2.stop()

    def test_kill_switch_restores_serial_path(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv("JTPU_SERVE_BATCH", "0")
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu")
        assert cfg.batch_enabled is False
        d = serve_ns.CheckDaemon(cfg)
        assert d.batcher is None     # no scheduler object at all
        d.start()
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops(3)})
        assert code == 202
        doc = _wait_done(d, body["id"])
        assert doc["result"]["valid"] is True
        assert "gang" not in doc["result"]["serve"]
        d.stop()

    def test_batch_max_one_disables_scheduler(self, tmp_path):
        d = _daemon(tmp_path, batch_max=1)
        assert d.batcher is None
        d.stop()

    def test_retry_after_ewma_divides_by_batch_size(self, tmp_path):
        """The Retry-After satellite: a gang's wall-clock is amortized
        over its realized batch size, so an 8-wide 8 s batch reads as
        1 s/request — not 8."""
        d = _daemon(tmp_path, queue_max=16)
        reqs = []
        for v in (1, 5, 9):
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops(3, value=v)})
            assert code == 202
        for _ in range(3):
            reqs.append(d._dequeue())
        d._finish(reqs[0], {"valid": True}, 8.0, batch_size=8)
        assert d._service_ewma == pytest.approx(1.0)
        d._finish(reqs[1], {"valid": True}, 4.0, batch_size=4)
        assert d._service_ewma == pytest.approx(1.0)   # same per-request
        d._finish(reqs[2], {"valid": True}, 2.0)       # serial: 2 s/req
        assert d._service_ewma == pytest.approx(0.3 * 2.0 + 0.7 * 1.0)
        d.stop()


class TestWalGangReplay:
    def test_torn_tail_mid_gang_replays_all_members(self, tmp_path):
        """A SIGKILL that tears the WAL mid-gang-record: every accepted
        member still replays (none had a done record), the torn gang
        line is skipped, and verdicts match offline."""
        histories = [_ops(3), _ops(4, value=9)]
        d1 = _daemon(tmp_path)
        for ops in histories:
            assert d1.submit({"model": "cas-register",
                              "history": ops})[0] == 202
        d1.journal.close()
        with open(d1.journal.path, "ab") as f:
            f.write(b'deadbeef {"event": "gang", "ids": [tor')  # torn
        pending, stats = serve_ns.RequestJournal.replay(d1.journal.path)
        assert len(pending) == 2 and stats["torn"] == 1
        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=150.0)
        assert d2.replay_stats["requeued"] == 2
        with d2._lock:
            rids = list(d2._by_id)
        docs = [_wait_done(d2, rid) for rid in rids]
        d2.stop()
        served = sorted(repr(doc["result"]["valid"]) for doc in docs)
        offline = sorted(repr(_offline(o)["valid"]) for o in histories)
        assert served == offline

    def test_complete_gang_records_are_replay_inert(self, tmp_path):
        """A COMPLETE gang record (all members done) must not re-queue
        anything: gang membership is evidence, not acceptance."""
        d1 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=100.0)
        ids = []
        for v in (1, 5):
            code, body, _ = d1.submit({"model": "cas-register",
                                       "history": _ops(3, value=v)})
            ids.append(body["id"])
        for rid in ids:
            _wait_done(d1, rid)
        d1.stop()
        pending, stats = serve_ns.RequestJournal.replay(d1.journal.path)
        assert pending == []
        assert stats["records"] >= 4   # accepted x2 (+gang) + done x2

    def test_interleaved_tenants_replay_in_acceptance_order(
            self, tmp_path):
        """Replay preserves WAL acceptance order across interleaved
        tenants; the re-formed gang then serves both tenants in one
        dispatch."""
        d1 = _daemon(tmp_path, queue_max=16)
        expect = []
        for i in range(4):
            code, body, _ = d1.submit(
                {"tenant": "ab"[i % 2], "model": "cas-register",
                 "history": _ops(3 + i, value=10 * i)})
            assert code == 202
            expect.append((body["id"], "ab"[i % 2]))
        d1.journal.close()
        pending, _ = serve_ns.RequestJournal.replay(d1.journal.path)
        assert [(p["id"], p["tenant"]) for p in pending] == expect
        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=200.0)
        assert d2.replay_stats["requeued"] == 4
        docs = [_wait_done(d2, rid) for rid, _ in expect]
        sizes = {doc["result"]["serve"]["gang"]["size"]
                 for doc in docs}
        tenants = {doc["tenant"] for doc in docs}
        assert sizes == {4} and tenants == {"a", "b"}
        d2.stop()


# ---------------------------------------------------------------------------
# Warm-state eviction (the --engine-max-buckets satellite)
# ---------------------------------------------------------------------------


class TestWarmEviction:
    def test_lru_evicts_oldest_warm_bucket(self):
        eng = Engine("evict-warm", max_warm_buckets=1)
        p1, kernel = _packed(_ops(2))
        p2, _ = _packed(_ops(40))          # a different shape bucket
        b1 = Engine.bucket_key(p1, kernel)
        b2 = Engine.bucket_key(p2, kernel)
        assert b1 != b2
        eng.warm(p1, kernel, rungs=1)
        eng.warm(p2, kernel, rungs=1)
        assert eng.warm_buckets() == [b2]  # LRU: oldest claim dropped
        assert eng.evictions == 1

    def test_touch_refreshes_lru_order(self):
        eng = Engine("evict-touch", max_warm_buckets=2)
        p1, kernel = _packed(_ops(2))
        p2, _ = _packed(_ops(40))
        p3, _ = _packed(_ops(10))
        keys = {Engine.bucket_key(p, kernel) for p in (p1, p2, p3)}
        assert len(keys) == 3, "need three distinct buckets"
        eng.warm(p1, kernel, rungs=1)
        eng.warm(p2, kernel, rungs=1)
        eng.warm(p1, kernel, rungs=1)      # touch: p1 is now newest
        eng.warm(p3, kernel, rungs=1)      # evicts p2, not p1
        assert Engine.bucket_key(p1, kernel) in eng.warm_buckets()
        assert Engine.bucket_key(p2, kernel) not in eng.warm_buckets()

    def test_env_and_setter_bound_the_claim(self, monkeypatch):
        monkeypatch.setenv("JTPU_ENGINE_MAX_BUCKETS", "3")
        assert Engine("env-bound").max_warm_buckets == 3
        monkeypatch.delenv("JTPU_ENGINE_MAX_BUCKETS")
        eng = Engine("set-bound")
        assert eng.max_warm_buckets == 0   # unbounded by default
        p1, kernel = _packed(_ops(2))
        p2, _ = _packed(_ops(40))
        eng.warm(p1, kernel, rungs=1)
        eng.warm(p2, kernel, rungs=1)
        eng.set_max_warm_buckets(1)        # trims immediately
        assert len(eng.warm_buckets()) == 1 and eng.evictions == 1

    def test_daemon_healthz_reports_eviction_state(self, tmp_path):
        d = _daemon(tmp_path, engine_max_buckets=2)
        assert d.engine.max_warm_buckets == 2
        health = d.healthz()
        assert health["engine"]["max-warm-buckets"] == 2
        assert health["engine"]["evictions"] == 0
        d.stop()


# ---------------------------------------------------------------------------
# Shared-secret auth (the --auth-token satellite)
# ---------------------------------------------------------------------------


class TestAuth:
    def _server(self, tmp_path, token):
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu", auth_token=token)
        return serve_ns.run_daemon(cfg, host="127.0.0.1", port=0,
                                   store_root=str(tmp_path / "store"))

    def test_post_routes_require_bearer_token(self, tmp_path):
        daemon, server = self._server(tmp_path, "s3cret")
        port = server.server_port
        doc = {"model": "cas-register", "history": _ops()}
        try:
            code, body, hdrs = _post(port, "/check", doc)
            assert code == 401 and body["error"] == "unauthorized"
            assert hdrs.get("WWW-Authenticate") == "Bearer"

            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/check",
                data=json.dumps(doc).encode(), method="POST",
                headers={"Authorization": "Bearer wrong"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 401

            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/check",
                data=json.dumps(doc).encode(), method="POST",
                headers={"Authorization": "Bearer s3cret"})
            with urllib.request.urlopen(req) as r:
                assert r.status == 202

            # metrics / healthz / the results browser stay open
            assert _get(port, "/healthz")[0] == 200
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                assert r.status == 200

            code, _, _ = _post(port, "/drain", None)
            assert code == 401     # drain is a mutating route too
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/drain", data=b"",
                method="POST",
                headers={"Authorization": "Bearer s3cret"})
            with urllib.request.urlopen(req) as r:
                assert json.load(r)["drained"] is True
        finally:
            server.shutdown()
            daemon.stop()

    def test_no_token_configured_keeps_routes_open(self, tmp_path):
        daemon, server = self._server(tmp_path, None)
        try:
            code, _, _ = _post(server.server_port, "/check",
                               {"model": "cas-register",
                                "history": _ops()})
            assert code == 202
        finally:
            server.shutdown()
            daemon.stop()

    def test_env_token_configures_daemon(self, monkeypatch, tmp_path):
        monkeypatch.setenv("JTPU_SERVE_TOKEN", "from-env")
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"))
        assert cfg.auth_token == "from-env"


# ---------------------------------------------------------------------------
# Request-scoped distributed tracing (doc/observability.md, "Request
# tracing"): one trace id from POST /check to verdict
# ---------------------------------------------------------------------------


class TestRequestTrace:
    def test_inbound_traceparent_honored_and_echoed(self, tmp_path):
        from jepsen_tpu.obs import trace as obs_trace
        tid = obs_trace.new_trace_id()
        d = _daemon(tmp_path, start=True)
        code, body, hdrs = d.submit(
            {"model": "cas-register", "history": _ops(),
             "traceparent": f"00-{tid}-00f067aa0ba902b7-01"})
        assert code == 202
        assert body["trace"] == tid
        got = obs_trace.parse_traceparent(hdrs.get("traceparent"))
        assert got is not None and got[0] == tid
        doc = _wait_done(d, body["id"])
        assert doc["trace"] == tid
        assert doc["result"]["serve"]["trace"] == tid
        d.stop()

    def test_minted_when_absent_or_malformed(self, tmp_path):
        d = _daemon(tmp_path, start=True)
        _, b1, _ = d.submit({"model": "cas-register",
                             "history": _ops()})
        _, b2, _ = d.submit({"model": "cas-register",
                             "history": _ops(3),
                             "traceparent": "garbage-header"})
        assert len(b1["trace"]) == 32 and int(b1["trace"], 16) >= 0
        assert len(b2["trace"]) == 32
        assert b1["trace"] != b2["trace"]   # one id PER request
        for b in (b1, b2):
            _wait_done(d, b["id"])
        d.stop()

    def test_phase_breakdown_sums_to_wall_time(self, tmp_path):
        d = _daemon(tmp_path, start=True)
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops()})
        assert code == 202
        doc = _wait_done(d, body["id"])
        serve_doc = doc["result"]["serve"]
        ph = serve_doc["phases"]
        assert set(ph) == {"queue_s", "coalesce_s", "compile_s",
                           "device_s", "verdict_s"}
        assert all(v >= 0 for v in ph.values())
        # the three check-side phases partition the measured wall time
        check_side = ph["compile_s"] + ph["device_s"] + ph["verdict_s"]
        assert abs(check_side - serve_doc["seconds"]) < 0.05
        assert ph["device_s"] > 0           # the check ran on device
        d.stop()

    def test_trace_artifact_spans_admission_to_verdict(self, tmp_path):
        from jepsen_tpu.obs import trace as obs_trace
        d = _daemon(tmp_path, start=True)
        code, body, _ = d.submit({"model": "cas-register",
                                  "history": _ops()})
        assert code == 202
        tid = body["trace"]
        _wait_done(d, body["id"])
        d.stop()                            # flushes + detaches sink
        path = os.path.join(d.config.root, "trace.jsonl")
        recs, stats = obs_trace.read_trace(path)
        assert stats["torn"] == 0 and stats["corrupt"] == 0
        assert any(r["name"] == "trace.sync" for r in recs)
        mine = obs_trace.by_trace(recs).get(tid, [])
        names = {r["name"] for r in mine}
        assert {"serve.request", "serve.verdict"} <= names
        # the device segment joined the same trace (a previously-run
        # suite may have warmed the engine's bucket already, in which
        # case engine.warm legitimately never runs — the fresh-process
        # CI gate asserts the full ≥4-phase waterfall)
        assert names & {"checker.segment", "engine.warm"}
        req_spans = [r for r in mine if r["name"] == "serve.request"]
        assert req_spans and req_spans[0]["id"] == body["id"]

    def test_replay_keeps_original_trace_id(self, tmp_path):
        from jepsen_tpu import journal as journal_ns
        from jepsen_tpu.obs import trace as obs_trace
        tid = obs_trace.new_trace_id()
        d1 = _daemon(tmp_path)
        code, body, _ = d1.submit(
            {"model": "cas-register", "history": _ops(),
             "traceparent": f"00-{tid}-00f067aa0ba902b7-01"})
        assert code == 202 and body["trace"] == tid
        d1.journal.close()                  # SIGKILL before any work
        d2 = _daemon(tmp_path, start=True)
        assert d2.replay_stats["requeued"] == 1
        with d2._lock:
            rid = next(iter(d2._by_id))
        doc = _wait_done(d2, rid)
        assert doc["trace"] == tid          # NOT a fresh mint
        assert doc["result"]["serve"]["trace"] == tid
        d2.stop()
        records, _ = journal_ns.read_json_records(d2.journal.path)
        accepted = [r for r in records if r.get("event") == "accepted"]
        assert accepted and all(r["trace"] == tid for r in accepted)

    def test_gang_members_traced_and_verdicts_bit_identical(
            self, tmp_path):
        """Tracing ON must not perturb gang verdicts: every member's
        verdict matches the offline serial path bit-for-bit, each
        member keeps its OWN trace id, and non-leaders link to the
        leader's trace via serve.gang.join."""
        from jepsen_tpu.obs import trace as obs_trace
        histories = [_ops(3), _ops(4, value=9), _ops(5, value=20)]
        d1 = _daemon(tmp_path)
        for ops in histories:
            code, _, _ = d1.submit({"model": "cas-register",
                                    "history": ops})
            assert code == 202
        d1.journal.close()
        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=200.0)
        with d2._lock:
            rids = list(d2._by_id)
        docs = [_wait_done(d2, rid) for rid in rids]
        assert d2.stats["batches"] >= 1
        d2.stop()
        tids = [doc["trace"] for doc in docs]
        assert len(set(tids)) == len(tids)  # one id per member
        for doc in docs:
            assert doc["result"]["serve"]["gang"]["size"] >= 2
            assert doc["result"]["serve"]["trace"] == doc["trace"]
            assert "phases" in doc["result"]["serve"]
        served = sorted(repr(doc["result"]["valid"]) for doc in docs)
        offline = sorted(repr(_offline(o)["valid"]) for o in histories)
        assert served == offline
        recs, _ = obs_trace.read_trace(
            os.path.join(d2.config.root, "trace.jsonl"))
        joins = [r for r in recs if r["name"] == "serve.gang.join"]
        assert joins                         # non-leaders linked
        leader_tid = joins[0]["leader"]
        assert leader_tid in tids
        assert all(j["trace"] != leader_tid for j in joins)
        gang_spans = [r for r in recs if r["name"] == "serve.gang"]
        assert gang_spans and \
            gang_spans[0]["trace"] == leader_tid

    def test_http_roundtrip_carries_traceparent(self, tmp_path):
        from jepsen_tpu.obs import trace as obs_trace
        tid = obs_trace.new_trace_id()
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu")
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0,
            store_root=str(tmp_path / "store"))
        port = server.server_port
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/check",
                data=json.dumps({"model": "cas-register",
                                 "history": _ops()}).encode(),
                method="POST",
                headers={"traceparent":
                         f"00-{tid}-00f067aa0ba902b7-01"})
            with urllib.request.urlopen(req) as r:
                assert r.status == 202
                body = json.load(r)
                assert body["trace"] == tid
                echoed = r.headers.get("traceparent")
            assert obs_trace.parse_traceparent(echoed)[0] == tid
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/check/"
                        f"{body['id']}") as r:
                    doc = json.load(r)
                    hdr = r.headers.get("traceparent")
                if doc["state"] == "done":
                    break
                time.sleep(0.05)
            assert doc["state"] == "done"
            assert doc["result"]["serve"]["phases"]["device_s"] > 0
            assert obs_trace.parse_traceparent(hdr)[0] == tid
            # the stitched waterfall renders over HTTP too
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/trace/request/"
                    f"{body['id']}") as r:
                page = r.read().decode()
            assert tid in page and "serve.request" in page
        finally:
            server.shutdown()
            daemon.stop()


class TestTraceKillSwitch:
    def test_off_leaves_no_trace_anywhere(self, tmp_path, monkeypatch):
        """JTPU_TRACE=0 is the identity: no trace ids minted, no
        traceparent echoed, no trace keys in the WAL, no trace.jsonl,
        no phases in results — byte-compatible with the pre-tracing
        daemon."""
        from jepsen_tpu import journal as journal_ns
        monkeypatch.setenv("JTPU_TRACE", "0")
        d = _daemon(tmp_path, start=True)
        code, body, hdrs = d.submit(
            {"model": "cas-register", "history": _ops(),
             "traceparent": "00-" + "ab" * 16
                            + "-00f067aa0ba902b7-01"})
        assert code == 202
        assert "trace" not in body
        assert "traceparent" not in hdrs
        doc = _wait_done(d, body["id"])
        assert "trace" not in doc
        assert "trace" not in doc["result"]["serve"]
        assert "phases" not in doc["result"]["serve"]
        d.stop()
        assert not os.path.exists(
            os.path.join(d.config.root, "trace.jsonl"))
        records, _ = journal_ns.read_json_records(d.journal.path)
        assert all("trace" not in r for r in records)

    def test_verdicts_identical_on_and_off(self, tmp_path,
                                           monkeypatch):
        ops = _ops(3)
        monkeypatch.setenv("JTPU_TRACE", "0")
        d_off = _daemon(tmp_path / "off", start=True)
        _, b_off, _ = d_off.submit({"model": "cas-register",
                                    "history": ops})
        r_off = _wait_done(d_off, b_off["id"])["result"]
        d_off.stop()
        monkeypatch.setenv("JTPU_TRACE", "1")
        d_on = _daemon(tmp_path / "on", start=True)
        _, b_on, _ = d_on.submit({"model": "cas-register",
                                  "history": ops})
        r_on = _wait_done(d_on, b_on["id"])["result"]
        d_on.stop()
        for key in ("valid", "levels", "rung", "work"):
            assert r_off.get(key) == r_on.get(key)


class TestTenantLatencyLabels:
    def test_queue_wait_labeled_per_tenant_with_exemplars(
            self, tmp_path):
        """Satellite: the queue-wait histogram is labeled per tenant
        (fairness is observable per tenant, not just in aggregate) and
        traced requests leave OpenMetrics exemplars pointing at their
        trace ids."""
        before = {
            t: serve_ns._QUEUE_WAIT.snapshot().get(
                f'{{tenant="{t}"}}', {"count": 0})["count"]
            for t in ("tenA", "tenB")}
        d = _daemon(tmp_path, start=True)
        rids, tids = [], {}
        for i in range(4):
            tenant = "tenA" if i % 2 == 0 else "tenB"
            code, body, _ = d.submit({"model": "cas-register",
                                      "tenant": tenant,
                                      "history": _ops(2 + i)})
            assert code == 202
            rids.append(body["id"])
            tids[body["id"]] = body["trace"]
        for rid in rids:
            _wait_done(d, rid)
        d.stop()
        snap = serve_ns._QUEUE_WAIT.snapshot()
        # fairness: BOTH tenants' waits were observed, two each —
        # neither tenant's latency hides in the other's series
        for t in ("tenA", "tenB"):
            series = snap.get(f'{{tenant="{t}"}}')
            assert series is not None, snap.keys()
            assert series["count"] - before[t] == 2
        lines = serve_ns._QUEUE_WAIT.expose()
        ex_lines = [ln for ln in lines if " # {trace_id=" in ln]
        assert ex_lines, "no exemplar on any queue-wait bucket"
        assert any(tid in ln for ln in ex_lines
                   for tid in tids.values())

    def test_coalesce_wait_labeled_per_tenant(self, tmp_path):
        d1 = _daemon(tmp_path)
        for v in (1, 5):
            d1.submit({"model": "cas-register", "tenant": "gangT",
                       "history": _ops(3, value=v)})
        d1.journal.close()
        d2 = _daemon(tmp_path, start=True, workers=1,
                     batch_wait_ms=200.0)
        with d2._lock:
            rids = list(d2._by_id)
        for rid in rids:
            _wait_done(d2, rid)
        assert d2.stats["batches"] >= 1
        d2.stop()
        snap = serve_ns._COALESCE_WAIT.snapshot()
        series = snap.get('{tenant="gangT"}')
        assert series is not None and series["count"] >= 1


class TestOldestInflight:
    def test_healthz_and_watch_line_surface_age(self, tmp_path):
        from jepsen_tpu.obs import observatory
        d = _daemon(tmp_path)
        assert d.healthz()["oldest-inflight-s"] is None
        req = serve_ns.CheckRequest(id="r-stuck", tenant="t",
                                    model="cas-register", history=[])
        req.started_at = time.monotonic() - 12.5
        with d._lock:
            d._inflight[req.id] = req
        age = d.healthz()["oldest-inflight-s"]
        assert age is not None and 12.0 < age < 14.0
        d._publish(force=True)
        p = observatory.read_progress(d.config.root)
        line = observatory.format_status(p)
        assert "oldest-inflight 12." in line
        with d._lock:
            del d._inflight[req.id]
        d._publish(force=True)
        p = observatory.read_progress(d.config.root)
        assert "oldest-inflight" not in observatory.format_status(p)

    def test_age_counts_from_dequeue_not_submit(self, tmp_path):
        d = _daemon(tmp_path)
        req = serve_ns.CheckRequest(id="r-q", tenant="t",
                                    model="cas-register", history=[])
        req.queued_at = time.monotonic() - 100.0   # long queue wait
        req.started_at = time.monotonic() - 2.0    # just dequeued
        with d._lock:
            d._inflight[req.id] = req
        age = d.healthz()["oldest-inflight-s"]
        assert age is not None and age < 5.0


class TestTracerAttachRace:
    def test_attach_detach_races_serve_workers(self, tmp_path):
        """Satellite: re-pointing the tracer sink while serve workers
        stream spans must neither raise nor tear lines — every record
        lands whole in whichever file held the sink."""
        from jepsen_tpu.obs import trace as obs_trace
        d = _daemon(tmp_path, start=True, workers=2)
        paths = [str(tmp_path / f"alt{i}.jsonl") for i in range(2)]
        stop = threading.Event()
        errors = []

        def flipper():
            i = 0
            try:
                while not stop.is_set():
                    obs_trace.tracer().attach(paths[i % 2])
                    i += 1
                    time.sleep(0.001)
            except Exception as e:          # pragma: no cover
                errors.append(e)

        t = threading.Thread(target=flipper)
        t.start()
        try:
            rids = []
            for i in range(6):
                code, body, _ = d.submit({"model": "cas-register",
                                          "history": _ops(2 + i % 3)})
                assert code == 202
                rids.append(body["id"])
            for rid in rids:
                _wait_done(d, rid)
        finally:
            stop.set()
            t.join()
            obs_trace.tracer().detach()
            d.stop()
        assert not errors
        total = 0
        for p in paths:
            if os.path.exists(p):
                recs, stats = obs_trace.read_trace(p)
                assert stats["torn"] == 0 and stats["corrupt"] == 0
                total += stats["spans"]
        assert total > 0                    # the races did overlap


# ---------------------------------------------------------------------------
# Per-tenant rate limiting (the fleet-capacity-aware 429 satellite)
# ---------------------------------------------------------------------------


class TestRateLimit:
    def test_token_bucket_refills_lazily(self):
        tb = serve_ns.TokenBucket(10.0, 2)
        assert tb.take() == 0.0
        assert tb.take() == 0.0            # burst of 2 admits two
        wait = tb.take()
        assert 0.0 < wait <= 0.1           # then ~1/rate until a token
        time.sleep(wait + 0.02)
        assert tb.take() == 0.0            # refilled

    def test_burst_exceeded_gets_429_with_retry_after(self, tmp_path):
        d = _daemon(tmp_path, queue_max=32, rate_limit=0.5, rate_burst=2)
        try:
            for _ in range(2):
                code, _, _ = d.submit({"model": "cas-register",
                                       "history": _ops(),
                                       "tenant": "bursty"})
                assert code == 202
            code, body, hdrs = d.submit({"model": "cas-register",
                                         "history": _ops(),
                                         "tenant": "bursty"})
            assert code == 429
            assert body["error"] == "rate-limited"
            assert body["retry-after-s"] > 0
            assert "Retry-After" in hdrs
            assert d.stats["rate-limited"] == 1
            # an independent tenant still has its own full bucket
            code, _, _ = d.submit({"model": "cas-register",
                                   "history": _ops(), "tenant": "calm"})
            assert code == 202
        finally:
            d.stop()

    def test_replay_bypasses_rate_limit(self, tmp_path):
        """WAL replay re-admits accepted requests regardless of the
        limiter: the 202 was already promised in a prior life."""
        d = _daemon(tmp_path, queue_max=32, rate_limit=0.5, rate_burst=1)
        try:
            for i in range(3):
                code, _, _ = d.submit({"model": "cas-register",
                                       "history": _ops(2 + i),
                                       "tenant": "replayed"},
                                      replayed=True)
                assert code == 202
            assert d.stats["rate-limited"] == 0
        finally:
            d.stop()

    def test_no_limit_by_default(self, tmp_path):
        d = _daemon(tmp_path, queue_max=32)
        try:
            assert d.config.rate_limit == 0.0
            for _ in range(8):
                code, _, _ = d.submit({"model": "cas-register",
                                       "history": _ops(),
                                       "tenant": "free"})
                assert code == 202
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# Fleet-width-aware Retry-After (satellite: EWMA x live host count)
# ---------------------------------------------------------------------------


class TestRetryAfterFleetWidth:
    def _stub_placer(self, width):
        import types
        return types.SimpleNamespace(width=lambda: width,
                                     live=lambda: width,
                                     hosts=[None] * width,
                                     stats={"remeshes": 0},
                                     stop=lambda: None)

    def test_ewma_tracks_host_seconds(self, tmp_path):
        """An 8 s gang of 8 on a 4-host fleet burned 32 host-seconds:
        4 host-seconds/request, NOT 1 — so the hint survives a shrink
        to one host without underestimating."""
        d = _daemon(tmp_path, queue_max=16)
        d.placer = self._stub_placer(4)
        try:
            code, body, _ = d.submit({"model": "cas-register",
                                      "history": _ops(3)})
            assert code == 202
            req = d._dequeue()
            d._finish(req, {"valid": True}, 8.0, batch_size=8)
            assert d._service_ewma == pytest.approx(4.0)
        finally:
            d.placer = None
            d.stop()

    def test_retry_after_divides_by_live_width(self, tmp_path):
        """The same backlog reads 4x shorter on a 4-host fleet — and
        stretches right back when the fleet shrinks (host loss)."""
        d = _daemon(tmp_path, queue_max=16)
        try:
            for v in (1, 5):
                code, _, _ = d.submit({"model": "cas-register",
                                       "history": _ops(3, value=v)})
                assert code == 202
            d._service_ewma = 20.0
            single = d._retry_after()
            d.placer = self._stub_placer(4)
            quad = d._retry_after()
            assert quad == pytest.approx(single / 4)
            d.placer = self._stub_placer(1)     # fleet lost 3 hosts
            assert d._retry_after() == pytest.approx(single)
        finally:
            d.placer = None
            d.stop()


# ---------------------------------------------------------------------------
# Breaker DCN-neutrality (satellite: fleet-retried classes don't trip)
# ---------------------------------------------------------------------------


class TestBreakerDcnNeutral:
    BUCKET = ("cas-register", 16, 0, 32)

    def test_dcn_class_failures_do_not_trip(self):
        from jepsen_tpu.resilience import DCN, TRANSIENT
        br = serve_ns.CircuitBreaker(2, 0.05)
        for cls in (DCN, TRANSIENT, DCN, DCN):
            br.record(self.BUCKET, cls, probe=False)
        ok, _, _ = br.allow(self.BUCKET)
        assert ok, "fleet-retried DCN failures must not open the breaker"
        assert br.open_count() == 0

    def test_dcn_neither_trips_nor_resets(self):
        """Neutral means neutral: a DCN blip between two real OOMs
        neither counts toward the threshold nor wipes the first OOM's
        strike."""
        from jepsen_tpu.resilience import DCN, OOM
        br = serve_ns.CircuitBreaker(2, 0.05)
        br.record(self.BUCKET, OOM, probe=False)
        br.record(self.BUCKET, DCN, probe=False)    # neutral
        rec = list(br.snapshot().values())[0]
        assert rec["fails"] == 1                    # not reset to 0
        br.record(self.BUCKET, OOM, probe=False)
        ok, _, _ = br.allow(self.BUCKET)
        assert not ok                               # 2 real strikes trip

    def test_dcn_probe_frees_the_slot(self):
        """A half-open probe that ends in a DCN blip must release the
        probe slot (else the breaker wedges half-open forever) without
        closing or re-opening."""
        import random as _random
        from jepsen_tpu.resilience import DCN, OOM
        br = serve_ns.CircuitBreaker(1, 0.05, rng=_random.Random(7))
        br.record(self.BUCKET, OOM, probe=False)
        time.sleep(0.08)
        ok, _, probe = br.allow(self.BUCKET)
        assert ok and probe
        br.record(self.BUCKET, DCN, probe=True)     # inconclusive probe
        ok, _, probe = br.allow(self.BUCKET)
        assert ok and probe, "slot freed: the NEXT probe may run"


# ---------------------------------------------------------------------------
# Byte-based warm eviction (the headroom-driven satellite)
# ---------------------------------------------------------------------------


class TestByteEviction:
    def _warm_two(self, eng):
        p1, kernel = _packed(_ops(2))
        p2, _ = _packed(_ops(40))
        b1 = Engine.bucket_key(p1, kernel)
        b2 = Engine.bucket_key(p2, kernel)
        assert b1 != b2
        eng.warm(p1, kernel, rungs=1)
        eng.warm(p2, kernel, rungs=1)
        return b1, b2

    def test_warm_records_carry_bytes(self):
        eng = Engine("bytes-rec")
        self._warm_two(eng)
        assert eng.warm_bytes() > 0        # plan-priced, not guessed

    def test_bytes_budget_trims_stalest_first(self):
        eng = Engine("bytes-budget")
        b1, b2 = self._warm_two(eng)
        total = eng.warm_bytes()
        eng.set_max_warm_bytes(total - 1)  # over budget by one byte
        assert eng.warm_buckets() == [b2]  # stalest (b1) evicted
        assert eng.evictions == 1

    def test_bytes_budget_keeps_newest_bucket(self):
        """Even an absurd 1-byte budget never evicts the LAST warm
        bucket — the serving path must keep its working set."""
        eng = Engine("bytes-floor")
        _, b2 = self._warm_two(eng)
        eng.set_max_warm_bytes(1)
        assert eng.warm_buckets() == [b2]

    def test_env_budget_wired(self, monkeypatch):
        monkeypatch.setenv("JTPU_ENGINE_BYTES_BUDGET", "12345")
        assert Engine("env-bytes").max_warm_bytes == 12345

    def test_evict_below_headroom_driven_by_gauge(self):
        """Memory pressure (headroom below the floor) evicts stalest
        buckets one at a time until the gauge recovers — count-blind,
        byte-driven."""
        eng = Engine("headroom")
        b1, b2 = self._warm_two(eng)
        ratios = iter([0.01, 0.05])        # starved, then recovered
        n = eng.evict_below_headroom(0.02, poll=lambda: next(ratios))
        assert n == 1
        assert eng.warm_buckets() == [b2]

    def test_evict_below_headroom_stops_at_last_bucket(self):
        eng = Engine("headroom-floor")
        self._warm_two(eng)
        n = eng.evict_below_headroom(0.5, poll=lambda: 0.0)
        assert n == 1                      # evicted down to one...
        assert len(eng.warm_buckets()) == 1   # ...then stopped

    def test_evict_below_headroom_no_pressure_is_noop(self):
        eng = Engine("headroom-ok")
        self._warm_two(eng)
        assert eng.evict_below_headroom(0.02, poll=lambda: 0.9) == 0
        assert len(eng.warm_buckets()) == 2

    def test_healthz_reports_byte_state(self, tmp_path):
        d = _daemon(tmp_path, engine_bytes_budget=1 << 20)
        try:
            assert d.engine.max_warm_bytes == 1 << 20
            health = d.healthz()
            assert health["engine"]["max-warm-bytes"] == 1 << 20
            # the daemon shares the process-wide engine, so other
            # tests' warm buckets may already be claimed here
            assert health["engine"]["warm-bytes"] == \
                d.engine.warm_bytes()
        finally:
            d.stop()
