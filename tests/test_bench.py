"""The driver contract: bench.py prints exactly one JSON line with the
required keys, and the multichip dryrun entry runs on the virtual mesh.
A broken bench records nothing for the round, so it gets its own test."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBenchContract:
    def test_cpu_child_emits_one_json_line(self):
        env = dict(os.environ)
        env.update({
            "JEPSEN_BENCH_CHILD": "cpu",
            "JEPSEN_BENCH_N_OPS": "300",      # tiny: contract, not perf
            "JEPSEN_BENCH_SKIP_SECONDARY": "1",
            "JAX_PLATFORMS": "cpu",
        })
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=300)
        lines = [ln for ln in pr.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, pr.stdout + pr.stderr[-500:]
        rec = json.loads(lines[0])
        assert rec["metric"] == "cas-register-10k-op-linearize"
        assert rec["unit"] == "s"
        assert isinstance(rec["value"], (int, float))
        assert rec["vs_baseline"] > 0
        assert "cold_s" in rec
        assert pr.returncode == 0

    def test_orchestrator_routes_failed_probe_to_cpu_fallback(self):
        """When the init probe does not certify an accelerator (instant
        'cpu' on a plain host; a hang within the operator-capped timeout
        on a host whose ambient plugin overrides JAX_PLATFORMS — observed
        with the axon plugin, which pins its own platform at import), the
        orchestrator must skip both TPU attempts and emit the contract
        line from the CPU fallback."""
        env = dict(os.environ)
        env.pop("JEPSEN_ACCEL_OK", None)         # force the probe path
        env.pop("JEPSEN_BENCH_SKIP_PROBE", None)
        env.update({
            "JEPSEN_BENCH_N_OPS": "300",
            "JEPSEN_BENCH_SKIP_SECONDARY": "1",
            "JEPSEN_BENCH_BUDGET_S": "280",
            "JEPSEN_ACCEL_PROBE_TIMEOUT": "5",
            "JAX_PLATFORMS": "cpu",
        })
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=300)
        lines = [ln for ln in pr.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, pr.stdout + pr.stderr[-500:]
        rec = json.loads(lines[0])
        assert rec["platform"] == "cpu"
        assert isinstance(rec["value"], (int, float))
        assert "# bench: probe:" in pr.stderr
        assert "trying platform=tpu" not in pr.stderr
        assert pr.returncode == 0

    def test_graft_entry_compiles_single_device(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        pr = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "import __graft_entry__ as g; fn, args = g.entry(); "
             "print(jax.jit(fn)(*args))"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=300)
        assert pr.returncode == 0, pr.stderr[-800:]
