"""The driver contract: bench.py prints exactly one JSON line with the
required keys, and the multichip dryrun entry runs on the virtual mesh.
A broken bench records nothing for the round, so it gets its own test."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBenchContract:
    def test_cpu_child_emits_one_json_line(self):
        env = dict(os.environ)
        env.update({
            "JEPSEN_BENCH_CHILD": "cpu",
            "JEPSEN_BENCH_N_OPS": "300",      # tiny: contract, not perf
            "JEPSEN_BENCH_SKIP_SECONDARY": "1",
            "JAX_PLATFORMS": "cpu",
        })
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=300)
        lines = [ln for ln in pr.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, pr.stdout + pr.stderr[-500:]
        rec = json.loads(lines[0])
        assert rec["metric"] == "cas-register-10k-op-linearize"
        assert rec["unit"] == "s"
        assert isinstance(rec["value"], (int, float))
        assert rec["vs_baseline"] > 0
        assert "cold_s" in rec
        assert pr.returncode == 0

    def test_graft_entry_compiles_single_device(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        pr = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "import __graft_entry__ as g; fn, args = g.entry(); "
             "print(jax.jit(fn)(*args))"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=300)
        assert pr.returncode == 0, pr.stderr[-800:]
