"""The driver contract: bench.py prints exactly one JSON line with the
required keys, and the multichip dryrun entry runs on the virtual mesh.
A broken bench records nothing for the round, so it gets its own test.
tools/bench_gate.py (the BENCH_r*.json trajectory regression gate) is
covered here too — it is what finally makes the trajectory actionable
in CI."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_records(d, values, cold=None, platform="cpu", start=1):
    for i, v in enumerate(values):
        n = start + i
        parsed = None
        if v is not None:
            parsed = {"metric": "m", "value": v, "unit": "s",
                      "vs_baseline": 1.0, "platform": platform}
            if cold is not None:
                parsed["cold_s"] = cold[i]
        with open(os.path.join(d, f"BENCH_r{n:02d}.json"), "w") as f:
            json.dump({"n": n, "cmd": "x", "rc": 0 if parsed else 1,
                       "tail": "", "parsed": parsed}, f)


class TestBenchGate:
    def test_passes_on_the_committed_trajectory(self):
        gate = _bench_gate().gate(REPO)
        assert gate["ok"], gate

    def test_flags_a_synthetic_2x_slowdown(self, tmp_path):
        bg = _bench_gate()
        _write_records(str(tmp_path), [0.6, 0.61, 0.62, 1.22],
                       cold=[1.1, 1.2, 1.3, 1.25])
        doc = bg.gate(str(tmp_path))
        assert not doc["ok"]
        (value_check,) = [c for c in doc["checks"]
                          if c["axis"] == "value"]
        assert value_check["status"] == "regression"
        # cold stayed in band: only the warm axis fails
        (cold_check,) = [c for c in doc["checks"]
                         if c["axis"] == "cold_s"]
        assert cold_check["status"] == "ok"

    def test_cold_regression_flags_independently(self, tmp_path):
        bg = _bench_gate()
        _write_records(str(tmp_path), [0.6, 0.61, 0.62, 0.6],
                       cold=[1.0, 1.2, 1.1, 9.0])
        doc = bg.gate(str(tmp_path))
        assert not doc["ok"]
        (cold_check,) = [c for c in doc["checks"]
                         if c["axis"] == "cold_s"]
        assert cold_check["status"] == "regression"

    def test_cross_platform_records_are_not_compared(self, tmp_path):
        bg = _bench_gate()
        # a tpu 9s record must not poison the cpu median (and vice
        # versa) — exactly the committed trajectory's shape
        _write_records(str(tmp_path), [9.0], platform="tpu", start=1)
        _write_records(str(tmp_path), [0.6, 0.61, 0.62], start=2)
        doc = bg.gate(str(tmp_path))
        assert doc["ok"]
        assert doc["comparable-priors"] == 2

    def test_short_trajectory_passes_with_note(self, tmp_path):
        bg = _bench_gate()
        _write_records(str(tmp_path), [0.6, 1.8])
        doc = bg.gate(str(tmp_path))
        assert doc["ok"]
        assert all(c["status"] == "skipped" for c in doc["checks"])

    def test_newest_without_measurement_fails(self, tmp_path):
        bg = _bench_gate()
        _write_records(str(tmp_path), [0.6, 0.61])
        with open(os.path.join(str(tmp_path), "BENCH_r03.json"),
                  "w") as f:
            json.dump({"n": 3, "rc": 1, "tail": "",
                       "parsed": {"metric": "m", "value": None,
                                  "unit": "s", "vs_baseline": 0,
                                  "error": "wedged"}}, f)
        doc = bg.gate(str(tmp_path))
        assert not doc["ok"]
        assert "no measurement" in doc["note"]

    def test_cli_json_format_and_exit_codes(self, tmp_path):
        _write_records(str(tmp_path), [0.6, 0.61, 0.62, 2.4])
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "bench_gate.py"),
             "--root", str(tmp_path), "--format", "json"],
            capture_output=True, text=True, timeout=60)
        assert pr.returncode == 1
        doc = json.loads(pr.stdout)
        assert doc["ok"] is False
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "bench_gate.py"),
             "--root", str(tmp_path), "--tolerance", "10"],
            capture_output=True, text=True, timeout=60)
        assert pr.returncode == 0
        assert "clean" in pr.stdout


class TestBenchContract:
    def test_cpu_child_emits_one_json_line(self):
        env = dict(os.environ)
        env.update({
            "JEPSEN_BENCH_CHILD": "cpu",
            "JEPSEN_BENCH_N_OPS": "300",      # tiny: contract, not perf
            "JEPSEN_BENCH_SKIP_SECONDARY": "1",
            "JAX_PLATFORMS": "cpu",
        })
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=300)
        lines = [ln for ln in pr.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, pr.stdout + pr.stderr[-500:]
        rec = json.loads(lines[0])
        assert rec["metric"] == "cas-register-10k-op-linearize"
        assert rec["unit"] == "s"
        assert isinstance(rec["value"], (int, float))
        assert rec["vs_baseline"] > 0
        assert "cold_s" in rec
        assert pr.returncode == 0

    def test_orchestrator_routes_failed_probe_to_cpu_fallback(self):
        """When the init probe does not certify an accelerator (instant
        'cpu' on a plain host; a hang within the operator-capped timeout
        on a host whose ambient plugin overrides JAX_PLATFORMS — observed
        with the axon plugin, which pins its own platform at import), the
        orchestrator must skip both TPU attempts and emit the contract
        line from the CPU fallback."""
        env = dict(os.environ)
        env.pop("JEPSEN_ACCEL_OK", None)         # force the probe path
        env.pop("JEPSEN_BENCH_SKIP_PROBE", None)
        env.update({
            "JEPSEN_BENCH_N_OPS": "300",
            "JEPSEN_BENCH_SKIP_SECONDARY": "1",
            "JEPSEN_BENCH_BUDGET_S": "280",
            "JEPSEN_ACCEL_PROBE_TIMEOUT": "5",
            "JAX_PLATFORMS": "cpu",
        })
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=300)
        lines = [ln for ln in pr.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, pr.stdout + pr.stderr[-500:]
        rec = json.loads(lines[0])
        assert rec["platform"] == "cpu"
        assert isinstance(rec["value"], (int, float))
        assert "# bench: probe:" in pr.stderr
        assert "trying platform=tpu" not in pr.stderr
        assert pr.returncode == 0

    def test_graft_entry_compiles_single_device(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        pr = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "import __graft_entry__ as g; fn, args = g.entry(); "
             "print(jax.jit(fn)(*args))"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=300)
        assert pr.returncode == 0, pr.stderr[-800:]
