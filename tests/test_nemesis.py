"""Nemesis tests: pure grudge math with no network (reference
nemesis_test.clj) plus dummy-control-plane partition/compose behavior."""

import pytest

from jepsen_tpu import control, net, nemesis
from jepsen_tpu.history import Op


def nop(f, value=None):
    return Op(type="invoke", f=f, value=value, process="nemesis", time=0)


class TestBisect:
    def test_cases(self):
        assert nemesis.bisect([]) == [[], []]
        assert nemesis.bisect([1]) == [[], [1]]
        assert nemesis.bisect([1, 2, 3, 4]) == [[1, 2], [3, 4]]
        assert nemesis.bisect([1, 2, 3, 4, 5]) == [[1, 2], [3, 4, 5]]


class TestSplitOne:
    def test_loner(self):
        assert nemesis.split_one([1, 2, 3], loner=2) == [[2], [1, 3]]

    def test_random_loner(self):
        parts = nemesis.split_one([1, 2, 3])
        assert len(parts[0]) == 1 and len(parts[1]) == 2
        assert set(parts[0]) | set(parts[1]) == {1, 2, 3}


class TestCompleteGrudge:
    def test_bisected(self):
        assert nemesis.complete_grudge(nemesis.bisect([1, 2, 3, 4, 5])) == {
            1: {3, 4, 5},
            2: {3, 4, 5},
            3: {1, 2},
            4: {1, 2},
            5: {1, 2},
        }

    def test_empty(self):
        assert nemesis.complete_grudge([]) == {}


class TestBridge:
    def test_five(self):
        assert nemesis.bridge([1, 2, 3, 4, 5]) == {
            1: {4, 5},
            2: {4, 5},
            4: {1, 2},
            5: {1, 2},
        }


class TestMajoritiesRing:
    def test_properties(self):
        nodes = list(range(5))
        grudge = nemesis.majorities_ring(nodes)
        assert len(grudge) == 5
        assert set(grudge) == set(nodes)
        for node, snubbed in grudge.items():
            assert len(snubbed) == 2
            assert node not in snubbed
        assert len({frozenset(v) for v in grudge.values()}) == 5

    def test_five_node_ring_walk(self):
        # degenerate 5-node case: each node sees its two ring neighbors
        # symmetrically; the visibility graph is a single ring traversable
        # out and back (reference nemesis_test.clj:50-87)
        nodes = list(range(5))
        grudge = nemesis.majorities_ring(nodes)
        U = set(grudge)
        start = next(iter(grudge))
        frm, node, returning, path = None, start, False, []
        for _ in range(2 * len(U) + 2):
            vis = U - grudge[node]
            assert len(vis) == 3
            assert node in vis
            if frm is not None and node == start:
                if returning:
                    path.append(node)
                    break
                frm, node, returning = node, frm, True
                path.append(node)
            else:
                nxt = next(iter(vis - {node, frm}))
                frm, node = node, nxt
                path.append(frm)
        assert set(path) == U
        assert path == path[::-1]
        assert len(path) == 2 * len(U) + 1

    def test_larger_rings(self):
        for n in (7, 9, 11):
            nodes = list(range(n))
            grudge = nemesis.majorities_ring(nodes)
            from jepsen_tpu.util import majority
            m = majority(n)
            assert len(grudge) == n
            for node, snubbed in grudge.items():
                assert len(snubbed) == n - m
                assert node not in snubbed


def dummy_test(**over):
    test = {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": 5,
        "ssh": {"mode": "dummy"},
        "net": net.iptables(),
    }
    test.update(over)
    return test


def logs(test):
    return {node: list(s.log)
            for node, s in test.get("_sessions", {}).items()}


class TestPartitioner:
    def test_start_cuts_stop_heals(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nemesis.partition_halves().setup(test)
            out = n.invoke(test, nop("start"))
            assert out.value.startswith("Cut off")
            cmds = logs(test)
            # n1, n2 drop from {n3,n4,n5}; n3..n5 drop from {n1,n2}
            assert sum("iptables -A INPUT" in c
                       for c in cmds["n1"]) == 3
            assert sum("iptables -A INPUT" in c
                       for c in cmds["n3"]) == 2
            out = n.invoke(test, nop("stop"))
            assert out.value == "fully connected"
            assert any("iptables -F" in c for c in logs(test)["n1"])

    def test_unknown_f_raises(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nemesis.partition_halves().setup(test)
            with pytest.raises(ValueError):
                n.invoke(test, nop("zap"))


class Recorder(nemesis.Nemesis):
    def __init__(self, name="rec"):
        self.name = name
        self.calls = []

    def invoke(self, t, op):
        self.calls.append(op.f)
        return op


class TestCompose:
    def test_routes_by_set(self):
        part, killer = Recorder("part"), Recorder("kill")
        n = nemesis.compose({
            frozenset({"start", "stop"}): part,
            frozenset({"kill"}): killer,
        }).setup({})
        out = n.invoke({}, nop("start"))
        assert out.f == "start"
        n.invoke({}, nop("kill"))
        assert part.calls == ["start"] and killer.calls == ["kill"]

    def test_dict_spec_renames_f(self):
        # two partitioners both speaking start/stop, disambiguated by
        # renaming dict specs (nemesis.clj compose docstring); dicts are
        # unhashable keys, so compose also takes (spec, nemesis) pairs
        a, b = Recorder("a"), Recorder("b")
        n = nemesis.compose([
            ({"split-start": "start", "split-stop": "stop"}, a),
            ({"ring-start": "start", "ring-stop": "stop"}, b),
        ]).setup({})
        out = n.invoke({}, nop("ring-start"))
        assert out.f == "ring-start"   # outer f restored
        assert a.calls == [] and b.calls == ["start"]  # inner f renamed

    def test_callable_spec(self):
        r = Recorder()
        n = nemesis.compose([
            (lambda f: f.removeprefix("x-") if f.startswith("x-") else None,
             r),
        ])
        n.invoke({}, nop("x-go"))
        assert r.calls == ["go"]

    def test_no_route_raises(self):
        n = nemesis.compose({frozenset({"start"}): nemesis.noop()})
        with pytest.raises(ValueError):
            n.invoke({}, nop("bogus"))


class TestNodeStartStopper:
    def test_start_stop_cycle(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nemesis.hammer_time("java", targeter=lambda ns: ns[0])
            out = n.invoke(test, nop("start"))
            assert out.type == "info"
            assert out.value == {"n1": ["paused", "java"]}
            assert any("killall -s STOP java" in c
                       for c in logs(test)["n1"])
            # double start refuses
            out2 = n.invoke(test, nop("start"))
            assert "already disrupting" in str(out2.value)
            out3 = n.invoke(test, nop("stop"))
            assert out3.value == {"n1": ["resumed", "java"]}
            out4 = n.invoke(test, nop("stop"))
            assert out4.value == "not-started"

    def test_no_target_skips(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nemesis.node_start_stopper(
                lambda ns: None, lambda t, nd: "x", lambda t, nd: "y")
            out = n.invoke(test, nop("start"))
            assert out.value == "no-target"


class TestTruncateFile:
    def test_truncate_plan(self):
        test = dummy_test()
        with control.session_pool(test):
            n = nemesis.truncate_file()
            plan = {"n2": {"file": "/var/lib/db/wal", "drop": 64}}
            n.invoke(test, nop("truncate", value=plan))
            assert any("truncate -c -s -64 /var/lib/db/wal" in c
                       for c in logs(test)["n2"])
