"""End-to-end in-process runtime tests — mirrors reference core_test.clj:
the full lifecycle (workers, generator, history, checker) against the
in-memory fake backend."""

import threading

import pytest

from jepsen_tpu import core, generator as gen
from jepsen_tpu.checker import linearizable, compose, unique_ids
from jepsen_tpu.history import NEMESIS, Op
from jepsen_tpu.models import CASRegister
from jepsen_tpu.testing import (
    AtomClient, FlakyClient, SharedRegister, atom_test, noop_test)


def run_no_store(test):
    test = dict(test)
    test["store-dir"] = None
    return core.run(test)


class TestNoop:
    def test_noop_run(self):
        t = run_no_store(noop_test())
        assert t["results"]["valid"] is True
        assert t["history"] == []


class TestBasicCas:
    # core_test.clj:17-28 basic-cas-test
    def test_cas_register_is_linearizable(self):
        t = atom_test()
        t["generator"] = gen.clients(
            gen.limit(200, gen.cas_gen(5)))
        t["checker"] = linearizable()
        t = run_no_store(t)
        assert t["results"]["valid"] is True
        # every invocation got a completion
        h = t["history"]
        assert len(h) >= 400
        opens = {}
        for o in h:
            if o.is_invoke:
                assert o.process not in opens
                opens[o.process] = o
            elif o.process != NEMESIS:
                assert o.process in opens
                del opens[o.process]
        assert not opens

    def test_history_indexed_and_timed(self):
        t = atom_test()
        t["generator"] = gen.clients(gen.limit(20, gen.cas_gen()))
        t["checker"] = linearizable()
        t = run_no_store(t)
        h = t["history"]
        assert [o.index for o in h] == list(range(len(h)))
        times = [o.time for o in h]
        assert all(t2 >= t1 for t1, t2 in zip(times, times[1:]))


class TestWorkerRecovery:
    # core_test.clj:86-101 worker-recovery-test: crashed clients must
    # reincarnate (p + concurrency) and the run still completes
    def test_flaky_client_reincarnation(self):
        reg = SharedRegister()
        t = atom_test(reg)
        t["client"] = FlakyClient(reg, flake_p=0.3, seed=7)
        t["generator"] = gen.clients(gen.limit(100, gen.cas_gen()))
        t["checker"] = linearizable()
        t = run_no_store(t)
        h = t["history"]
        infos = [o for o in h if o.is_info and o.process != NEMESIS]
        assert infos, "flaky client should produce indeterminate ops"
        # reincarnated processes appear: some process >= concurrency
        assert any(isinstance(o.process, int)
                   and o.process >= t["concurrency"] for o in h)
        # and the linearizability checker still passes: the register is
        # genuinely linearizable even with crashes
        assert t["results"]["valid"] is True, t["results"]

    def test_crashed_processes_consume_ops(self):
        # each op the generator hands out is either completed or crashed;
        # totals must balance
        reg = SharedRegister()
        t = atom_test(reg)
        t["client"] = FlakyClient(reg, flake_p=0.5, seed=3)
        n_ops = 60
        t["generator"] = gen.clients(gen.limit(n_ops, gen.cas_gen()))
        t = run_no_store(t)
        invokes = sum(1 for o in t["history"] if o.is_invoke)
        completions = sum(1 for o in t["history"] if not o.is_invoke)
        assert invokes == n_ops
        assert completions == n_ops


class TestNemesis:
    def test_nemesis_ops_in_history(self):
        class CountingNemesis:
            def __init__(self):
                self.invoked = []

            def setup(self, test):
                return self

            def invoke(self, test, op):
                self.invoked.append(op.f)
                return op.replace(type="info")

            def teardown(self, test):
                pass

        nem = CountingNemesis()
        t = atom_test()
        t["nemesis"] = nem
        t["generator"] = gen.Any_([
            gen.nemesis(gen.limit(4, gen.start_stop(0, 0))),
            gen.clients(gen.limit(50, gen.cas_gen())),
        ])
        t = run_no_store(t)
        assert nem.invoked == ["start", "stop", "start", "stop"]
        nem_ops = [o for o in t["history"] if o.process == NEMESIS]
        assert len(nem_ops) == 8  # 4 invokes + 4 completions


class TestPrimary:
    def test_primary_is_first_node(self):
        assert core.primary({"nodes": ["a", "b"]}) == "a"
        assert core.primary({"nodes": []}) is None


class TestSynchronizeBarrier:
    def test_db_setup_barrier(self):
        from jepsen_tpu import db as db_ns
        arrivals = []
        lock = threading.Lock()

        class BarrierDB(db_ns.DB):
            def setup(self, test, node):
                with lock:
                    arrivals.append(node)
                core.synchronize(test)

            def teardown(self, test, node):
                pass

        t = noop_test()
        t["db"] = BarrierDB()
        t = run_no_store(t)
        assert sorted(arrivals) == sorted(t["nodes"])


class TestStoreIntegration:
    def test_artifacts_written(self, tmp_path):
        t = atom_test()
        t["generator"] = gen.clients(gen.limit(10, gen.cas_gen()))
        t["checker"] = linearizable()
        t["store-root"] = str(tmp_path)
        t = core.run(t)
        d = t["store-dir"]
        import os
        files = set(os.listdir(d))
        assert {"history.txt", "history.jsonl", "test.json",
                "results.json", "jepsen.log"} <= files
        # round-trip
        from jepsen_tpu import store
        loaded = store.load(d)
        assert loaded["results"]["valid"] is True
        assert len(loaded["history"]) == len(t["history"])
        # latest symlinks
        assert os.path.islink(os.path.join(str(tmp_path), "latest"))
