"""Elastic fleet layer (jepsen_tpu.fleet): pool split/merge surgery at
the merge-sort barrier, host-loss re-meshing, work-stealing rebalance,
join admission, the DCN failure class, checkpoint resume across a
CHANGED mesh size, the JTPU_FLEET kill switch, and the obs/fleet.py
dead-host tolerance + watch/live imbalance surfacing satellites."""

import json
import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jepsen_tpu import fleet, resilience
from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker import tpu as T
from jepsen_tpu.checker import plan as plan_mod
from jepsen_tpu.checker.wgl import check_packed
from jepsen_tpu.fleet import (ElasticFleet, FleetPolicy, LocalHost,
                              check_packed_fleet, merge_pool,
                              repad_pool, shard_imbalance, split_pool)
from jepsen_tpu.models import CASRegister
from jepsen_tpu.ops.encode import pack_with_init
from jepsen_tpu.resilience import (DCN, TRANSIENT, Checkpoint,
                                   classify_failure,
                                   supervised_check_packed)
from jepsen_tpu.testing import simulate_register_history

pytestmark = pytest.mark.fleet


@pytest.fixture(autouse=True)
def fleet_env(monkeypatch):
    """Fleet tests must not inherit ambient fleet/plan knobs, and the
    kill switch must be provably OFF unless a test turns it on."""
    for var in ("JTPU_FLEET", "JTPU_FLEET_IMBALANCE_MAX",
                "JTPU_FLEET_IMBALANCE_LEVELS", "JTPU_FLEET_STEAL",
                "JTPU_FLEET_DEAD_S", "JTPU_PLAN_BYTES_LIMIT"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JEPSEN_RETRY_BASE", "0.001")
    yield


def _packed(seed=7, n=150, crash_p=0.02):
    h = simulate_register_history(n, n_procs=5, n_vals=4, seed=seed,
                                  crash_p=crash_p)
    return pack_with_init(h, CASRegister())


def fast_policy(**kw):
    from jepsen_tpu.resilience import RetryPolicy
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("backoff_cap_s", 0.01)
    return FleetPolicy(retry=RetryPolicy(**kw))


def _skewed_pool(cap=32, live=6, window=32, crw=8):
    """A synthetic pool with all live rows in shard 0's block —
    maximal straggler skew."""
    mw, mc = (window + 31) // 32, max((crw + 31) // 32, 1)
    k = np.zeros(cap, np.int32)
    k[:live] = np.arange(live, 0, -1, dtype=np.int32)
    mask = np.zeros((cap, mw), np.uint32)
    cmask = np.zeros((cap, mc), np.uint32)
    state = np.arange(cap, dtype=np.int32)
    alive = np.zeros(cap, bool)
    alive[:live] = True
    return (k, mask, cmask, state, alive)


class TestPoolSurgery:
    def test_split_contiguous_roundtrip(self):
        pool = _skewed_pool()
        parts = split_pool(pool, 4)
        assert len(parts) == 4
        assert all(p[0].shape[0] == 8 for p in parts)
        merged, dropped = merge_pool(parts, 32)
        assert not dropped
        # every live config survives the split+merge
        assert int(np.count_nonzero(merged[4])) == 6

    def test_split_interleave_deals_live_rows(self):
        pool = _skewed_pool(cap=32, live=6)
        before, _ = shard_imbalance(pool, 4)
        assert before == 4.0          # shard 0 hoards the frontier
        parts = split_pool(pool, 4, interleave=True)
        lives = [int(np.count_nonzero(p[4])) for p in parts]
        assert sorted(lives) == [1, 1, 2, 2]
        # the deal conserves every live config
        merged, _ = merge_pool(parts, 32)
        assert int(np.count_nonzero(merged[4])) == 6

    def test_merge_dedups_and_sorts_deepest_first(self):
        pool = _skewed_pool(cap=8, live=3)
        # duplicate the pool: every live config appears twice
        merged, dropped = merge_pool([pool, pool], 8)
        assert not dropped
        assert int(np.count_nonzero(merged[4])) == 3
        k, alive = merged[0], merged[4]
        live_k = k[alive]
        # deepest-first prefix, live rows compacted to the front
        assert list(live_k) == sorted(live_k, reverse=True)
        assert alive[:3].all() and not alive[3:].any()

    def test_merge_truncation_marks_dropped(self):
        pool = _skewed_pool(cap=8, live=8)
        other = _skewed_pool(cap=8, live=8)
        other[3][:] += 100            # distinct states: no dedup
        merged, dropped = merge_pool([pool, other], 8)
        assert dropped
        assert int(np.count_nonzero(merged[4])) == 8

    def test_repad_grow_and_shrink(self):
        pool = _skewed_pool(cap=8, live=4)
        grown, dropped = repad_pool(pool, 12)
        assert not dropped and grown[0].shape[0] == 12
        assert int(np.count_nonzero(grown[4])) == 4
        shrunk, dropped = repad_pool(grown, 4)
        assert not dropped and shrunk[0].shape[0] == 4
        _, dropped = repad_pool(pool, 2)   # live rows past the cut
        assert dropped

    def test_pool_sort_host_matches_device_orientation(self):
        # invalid rows sink; deeper (k + |mask|) rows lead
        k = np.array([1, 5, 3, 9], np.int32)
        mask = np.zeros((4, 1), np.uint32)
        mask[2, 0] = 0b111            # depth 3 + 3 = 6
        cmask = np.zeros((4, 1), np.uint32)
        state = np.zeros(4, np.int32)
        alive = np.array([True, True, True, False])
        perm = T._pool_sort_host(k, mask, cmask, state, alive)
        assert list(k[perm]) == [3, 5, 1, 9]   # 6, 5, 1 then dead


class TestRemeshValidation:
    def test_pad_for_axis(self):
        assert plan_mod.pad_for_axis(32, 3) == 33
        assert plan_mod.pad_for_axis(32, 4) == 32
        assert plan_mod.pad_for_axis(1, 8) == 8

    def test_check_remesh_pads_and_validates(self):
        p, _ = _packed()
        rm = plan_mod.check_remesh(p, 3, 32, 32, 8)
        assert rm["ok"] is True
        assert rm["capacity"] % 3 == 0 and rm["capacity"] >= 32
        assert rm["expand"] % 3 == 0
        assert rm["per-device-bytes"] > 0

    def test_check_remesh_never_raises_on_oom(self):
        p, _ = _packed()
        rm = plan_mod.check_remesh(p, 2, 16384, 32, 1024,
                                   bytes_limit=10_000)
        assert rm["ok"] is False
        assert any(i["rule"] == "PLAN-OOM" for i in rm["issues"])


class TestFleetSearch:
    def test_verdicts_match_single_host(self):
        for seed in (3, 7, 11):
            p, kernel = _packed(seed=seed)
            base = supervised_check_packed(p, kernel, segment_iters=8)
            out = check_packed_fleet(p, kernel, hosts=2,
                                     segment_iters=8)
            assert out["valid"] == base["valid"] == \
                check_packed(p, kernel)["valid"]
            assert out["fleet"]["hosts"] == ["host0", "host1"]
            assert out["segments"] >= 1
            assert out["segment-iters"] == 8

    def test_refutation_matches_and_carries_evidence(self):
        from jepsen_tpu.history import History, Op
        rows = [Op(type="invoke", f="write", value=1, process=0, time=0),
                Op(type="ok", f="write", value=1, process=0, time=1),
                Op(type="invoke", f="read", value=None, process=1,
                   time=2),
                Op(type="ok", f="read", value=9, process=1, time=3)]
        p, kernel = pack_with_init(History.of(rows), CASRegister())
        out = check_packed_fleet(p, kernel, hosts=2, segment_iters=4,
                                 capacity=32, window=32, expand=8)
        assert out["valid"] is False
        assert out.get("final-states")

    def test_host_kill_remeshes_and_verdict_survives(self):
        p, kernel = _packed()
        base = supervised_check_packed(p, kernel, segment_iters=2)

        def chaos(round_idx, fl):
            if round_idx == 2 and fl.hosts[1].state == "live":
                fl.hosts[1].kill()

        out = check_packed_fleet(p, kernel, hosts=2, segment_iters=2,
                                 on_round=chaos)
        assert out["valid"] == base["valid"]
        outcomes = [e.get("outcome") for e in out["attempts"]]
        assert "host-removed" in outcomes
        assert "remesh-to-1-hosts" in outcomes
        assert out["fleet"]["hosts-lost"] == 1
        assert out["fleet"]["remesh-count"] >= 1
        assert out["fleet"]["live"] == ["host0"]

    def test_all_hosts_lost_aborts_unknown(self):
        p, kernel = _packed()

        def chaos(round_idx, fl):
            for h in fl.hosts:
                h.kill()

        out = check_packed_fleet(p, kernel, hosts=2, segment_iters=2,
                                 on_round=chaos)
        assert out["valid"] is UNKNOWN
        assert "all fleet hosts lost" in out["error"]

    def test_steal_fires_on_skew_and_verdict_matches_no_steal(
            self, monkeypatch):
        p, kernel = _packed()
        monkeypatch.setenv("JTPU_FLEET_IMBALANCE_MAX", "1.01")
        monkeypatch.setenv("JTPU_FLEET_IMBALANCE_LEVELS", "1")
        out = check_packed_fleet(p, kernel, hosts=2, segment_iters=2)
        steals = [e for e in out["attempts"]
                  if e.get("outcome") == "steal-rebalance"]
        assert steals, "imbalance over threshold never stole"
        for s in steals:
            assert s["imbalance_after"] <= s["imbalance_before"]
        assert out["fleet"]["steal-count"] == len(steals)
        assert out["fleet"]["peak-imbalance"] > 1.01
        monkeypatch.setenv("JTPU_FLEET_STEAL", "0")
        nosteal = check_packed_fleet(p, kernel, hosts=2,
                                     segment_iters=2)
        assert nosteal["fleet"]["steal-count"] == 0
        assert nosteal["valid"] == out["valid"]

    def test_join_admitted_at_barrier(self):
        p, kernel = _packed()
        joined = []

        def chaos(round_idx, fl):
            if round_idx == 1 and not joined:
                h = LocalHost("late")
                joined.append(h)
                fl.request_join(h)

        out = check_packed_fleet(p, kernel, hosts=2, segment_iters=2,
                                 on_round=chaos)
        assert out["valid"] is True
        outcomes = [str(e.get("outcome", "")) for e in out["attempts"]]
        assert any(o.startswith("join-admitted-3-hosts")
                   for o in outcomes)
        assert "remesh-to-3-hosts" in outcomes
        assert out["fleet"]["hosts-joined"] == 1
        assert "late" in out["fleet"]["hosts"]

    def test_join_rejected_by_footprint(self):
        p, kernel = _packed()
        asked = []

        def chaos(round_idx, fl):
            if round_idx == 1 and not asked:
                asked.append(1)
                # the byte budget collapses mid-run: the would-be
                # third host's per-device footprint no longer fits
                os.environ["JTPU_PLAN_BYTES_LIMIT"] = "1"
                fl.request_join(LocalHost("late"))

        try:
            out = check_packed_fleet(p, kernel, hosts=2,
                                     segment_iters=2, on_round=chaos)
        finally:
            os.environ.pop("JTPU_PLAN_BYTES_LIMIT", None)
        outcomes = [e.get("outcome") for e in out["attempts"]]
        assert "join-rejected" in outcomes
        rej = next(e for e in out["attempts"]
                   if e.get("outcome") == "join-rejected")
        assert "PLAN-OOM" in rej["rules"]
        assert out["fleet"]["hosts-joined"] == 0
        assert "late" not in out["fleet"]["hosts"]

    def test_dcn_fault_retries_then_succeeds(self):
        p, kernel = _packed()
        boom = {"left": 2}

        def flaky(ctx):
            if boom["left"]:
                boom["left"] -= 1
                raise RuntimeError(
                    "DCN all-reduce collective timed out")

        hosts = [LocalHost("h0"), LocalHost("h1", chaos=flaky)]
        out = check_packed_fleet(p, kernel, hosts=hosts,
                                 segment_iters=8,
                                 policy=fast_policy())
        assert out["valid"] is True
        retries = [e for e in out["attempts"]
                   if e.get("event") == "host-retry"]
        assert len(retries) == 2
        assert all(r["class"] == DCN for r in retries)
        # both hosts survived: a slow interconnect degrades, it does
        # not remove the host
        assert out["fleet"]["hosts-lost"] == 0

    def test_dcn_retries_exhausted_removes_host(self):
        p, kernel = _packed()

        def always(ctx):
            raise RuntimeError("NCCL all-gather aborted")

        hosts = [LocalHost("h0"), LocalHost("h1", chaos=always)]
        out = check_packed_fleet(
            p, kernel, hosts=hosts, segment_iters=8,
            policy=fast_policy(max_retries=1))
        assert out["valid"] is True      # survivor finishes
        lost = [e for e in out["attempts"]
                if e.get("event") == "host-lost"]
        assert lost and lost[0]["host"] == "h1"
        assert lost[0]["class"] == DCN


class TestDCNClassification:
    def test_collective_text_classifies_dcn_not_transient(self):
        assert classify_failure(RuntimeError(
            "all-reduce DEADLINE_EXCEEDED across hosts")) == DCN
        assert classify_failure(RuntimeError(
            "NCCL ring broke")) == DCN
        assert classify_failure(RuntimeError(
            "coordination service heartbeat lost")) == DCN
        # plain transients stay transient
        assert classify_failure(RuntimeError(
            "UNAVAILABLE: connection dropped")) == TRANSIENT
        # OOM stays OOM even with collective-ish text nearby
        assert classify_failure(RuntimeError(
            "RESOURCE_EXHAUSTED during all-reduce")) == \
            resilience.OOM


class TestChangedMeshResume:
    """Satellite: Checkpoint save under N shards, resume under M —
    frontier rows conserved, verdict identical to uninterrupted."""

    def _fleet_cps(self, p, kernel, hosts):
        cps = []
        out = check_packed_fleet(p, kernel, hosts=hosts,
                                 segment_iters=2,
                                 on_checkpoint=cps.append)
        return out, cps

    @pytest.mark.parametrize("save_hosts,resume_hosts",
                             [(4, 2), (2, 4)])
    def test_fleet_resume_across_mesh_sizes(self, save_hosts,
                                            resume_hosts):
        p, kernel = _packed()
        base, cps = self._fleet_cps(p, kernel, save_hosts)
        assert cps, "search finished before any checkpoint"
        cp = cps[len(cps) // 2]
        live_saved = int(np.count_nonzero(np.asarray(cp.carry[4])))
        resumed = check_packed_fleet(p, kernel, hosts=resume_hosts,
                                     segment_iters=2, resume=cp)
        assert resumed["valid"] == base["valid"]
        # conservation: the resumed run's first split sees every live
        # frontier row the checkpoint carried (repad never drops)
        pool, dropped = repad_pool(
            cp.carry[:5],
            plan_mod.pad_for_axis(np.asarray(cp.carry[0]).shape[0],
                                  resume_hosts))
        assert not dropped
        assert int(np.count_nonzero(pool[4])) == live_saved

    @pytest.mark.parametrize("save_axis,resume_axis", [(4, 2), (2, 4)])
    def test_sharded_resume_bit_identical(self, save_axis, resume_axis):
        """The REAL sharded path: a checkpoint saved under a 4-shard
        mesh resumes under 2 (and 2 under 4) with verdict AND level
        count bit-identical to the uninterrupted search — the axis
        partitions rows, it never changes the math."""
        from jepsen_tpu import parallel
        from jepsen_tpu.checker.tpu import POOL_AXIS
        p, kernel = _packed(seed=11, n=120)
        mesh_a = parallel.make_mesh(save_axis, axis=POOL_AXIS)
        mesh_b = parallel.make_mesh(resume_axis, axis=POOL_AXIS)
        kw = dict(capacity=64, window=32, expand=16)
        unint = T.check_packed_sharded(p, kernel, mesh_a,
                                       segment_iters=4, **kw)
        cps = []
        T.check_packed_sharded(p, kernel, mesh_a, segment_iters=4,
                               on_checkpoint=cps.append, **kw)
        if len(cps) < 2:
            pytest.skip("search finished inside one segment")
        cp = cps[0]
        live_saved = int(np.count_nonzero(np.asarray(cp.carry[4])))
        resumed = T.check_packed_sharded(p, kernel, mesh_b,
                                         segment_iters=4, resume=cp,
                                         **kw)
        assert resumed["valid"] == unint["valid"]
        assert resumed["levels"] == unint["levels"]
        assert live_saved == int(np.count_nonzero(
            np.asarray(cp.carry[4])))

    def test_sharded_segmented_matches_monolithic(self):
        from jepsen_tpu import parallel
        from jepsen_tpu.checker.tpu import POOL_AXIS
        p, kernel = _packed(seed=5, n=100)
        mesh = parallel.make_mesh(2, axis=POOL_AXIS)
        kw = dict(capacity=64, window=32, expand=16)
        mono = T.check_packed_sharded(p, kernel, mesh, **kw)
        seg = T.check_packed_sharded(p, kernel, mesh,
                                     segment_iters=4, **kw)
        assert seg["valid"] == mono["valid"]
        assert seg["levels"] == mono["levels"]
        assert seg["segments"] >= 1
        assert seg["pool-sharding"] == "pool=2"


class TestKillSwitch:
    """JTPU_FLEET=0 (or absent) leaves single-host paths byte-identical
    — the same discipline as JTPU_TRACE / JTPU_PLAN_GATE."""

    def test_fleet_hosts_env_parsing(self, monkeypatch):
        assert T._fleet_hosts() == 0
        for off in ("0", "1", "", "  ", "nope", "-3"):
            monkeypatch.setenv("JTPU_FLEET", off)
            assert T._fleet_hosts() == 0
        monkeypatch.setenv("JTPU_FLEET", "2")
        assert T._fleet_hosts() == 2

    def test_off_and_absent_results_identical(self, monkeypatch):
        p, kernel = _packed()
        r_absent = T.check_packed_tpu(p, kernel, segment_iters=8)
        monkeypatch.setenv("JTPU_FLEET", "0")
        r_off = T.check_packed_tpu(p, kernel, segment_iters=8)

        def stable(r):
            r = dict(r)
            for k in ("device-s", "cost"):
                r.pop(k, None)
            return r

        assert stable(r_absent) == stable(r_off)
        assert "fleet" not in r_absent and "fleet" not in r_off

    def test_on_routes_through_fleet(self, monkeypatch):
        p, kernel = _packed()
        monkeypatch.setenv("JTPU_FLEET", "2")
        r = T.check_packed_tpu(p, kernel, segment_iters=8)
        assert r["fleet"]["hosts"] == ["host0", "host1"]
        monkeypatch.delenv("JTPU_FLEET")
        base = T.check_packed_tpu(p, kernel, segment_iters=8)
        assert r["valid"] == base["valid"]

    def test_off_leaves_history_artifact_byte_identical(
            self, monkeypatch, tmp_path):
        src = os.path.join(os.path.dirname(__file__), "fixtures",
                           "lint", "good_history.jsonl")
        art = tmp_path / "history.jsonl"
        art.write_bytes(open(src, "rb").read())
        before = art.read_bytes()
        from jepsen_tpu.history import History
        h = History.from_jsonl(art.read_text())
        v_absent = T.check_history_tpu(h, CASRegister())["valid"]
        assert art.read_bytes() == before
        monkeypatch.setenv("JTPU_FLEET", "0")
        v_off = T.check_history_tpu(h, CASRegister())["valid"]
        assert v_absent == v_off
        assert art.read_bytes() == before


class TestObsFleetDeadHosts:
    """Satellite: obs/fleet.py must render a vanished or torn host
    artifact dir as a host=dead row, never raise."""

    def _host_dir(self, tmp_path, name, level=5, hb_age=None):
        from jepsen_tpu.obs import fleet as obs_fleet
        d = tmp_path / name
        d.mkdir()
        (d / "progress.json").write_text(json.dumps(
            {"state": "searching", "level": level, "level-budget": 100,
             "ts": time.time()}))
        if hb_age is not None:
            (d / obs_fleet.HEARTBEAT_NAME).write_text(json.dumps(
                {"ts": time.time() - hb_age, "pid": 1}))
        return str(d)

    def test_vanished_dir_renders_dead_row(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        d1 = self._host_dir(tmp_path, "h1")
        gone = str(tmp_path / "h2")     # never created
        merged = obs_fleet.merge([d1, gone])
        rows = {r["host"]: r for r in merged["summary"]}
        assert rows["h2"]["state"] == "dead"
        assert rows["h2"]["missing"] is True
        assert rows["h1"]["state"] == "searching"
        lines = obs_fleet.format_fleet(merged)
        assert any("h2: host=dead" in ln for ln in lines)

    def test_stale_heartbeat_renders_dead(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        d1 = self._host_dir(tmp_path, "h1", hb_age=0.5)
        d2 = self._host_dir(tmp_path, "h2",
                            hb_age=obs_fleet.HEARTBEAT_DEAD_S + 60)
        merged = obs_fleet.merge([d1, d2])
        rows = {r["host"]: r for r in merged["summary"]}
        assert rows["h1"]["state"] == "searching"
        assert rows["h2"]["state"] == "dead"
        assert rows["h2"]["heartbeat-age-s"] > 60

    def test_torn_artifacts_tolerated(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        d = tmp_path / "h1"
        d.mkdir()
        (d / "metrics.json").write_text('{"jtpu_half": {"kind"')
        (d / "progress.json").write_text('{"state": "sear')
        (d / "trace.jsonl").write_text('{"name": "x", "ts"')
        merged = obs_fleet.merge([str(d)])
        assert merged["summary"][0]["host"] == "h1"

    def test_watch_fleet_cli_tolerates_vanished_dir(self, tmp_path,
                                                    capsys):
        from jepsen_tpu import cli
        d1 = self._host_dir(tmp_path, "h1")
        # finish the run so --once exits on its own
        (tmp_path / "h1" / "progress.json").write_text(json.dumps(
            {"state": "done", "level": 9, "ts": time.time()}))
        gone = str(tmp_path / "nope")
        rc = cli.run(cli.default_commands(),
                     ["watch", "--fleet", d1, gone, "--once"])
        assert rc == cli.OK
        text = capsys.readouterr().out
        assert "host=dead" in text
        # ALL dirs missing is still a usage error
        rc = cli.run(cli.default_commands(),
                     ["watch", "--fleet", str(tmp_path / "a"),
                      str(tmp_path / "b"), "--once"])
        assert rc == cli.INVALID_ARGS

    def test_discover_hosts_survives_vanishing_root(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        assert obs_fleet.discover_hosts(str(tmp_path / "gone")) == []


class TestImbalanceSurfacing:
    """Satellite: jtpu_shard_imbalance_ratio visible in watch / /live /
    the observatory ticker, not just bench's # search: line."""

    def test_format_status_renders_imbalance_and_fleet(self):
        from jepsen_tpu.obs import observatory
        line = observatory.format_status(
            {"state": "searching", "level": 4, "level-budget": 100,
             "imbalance": 2.5,
             "fleet": {"hosts": 3, "remeshes": 1, "steals": 2}})
        assert "imbalance 2.50x" in line
        assert "fleet 3 host(s)" in line
        assert "1 remesh(es)" in line and "2 steal(s)" in line

    def test_fleet_publishes_imbalance_to_progress(self, tmp_path,
                                                   monkeypatch):
        from jepsen_tpu.obs import observatory
        monkeypatch.setenv("JTPU_TRACE", "1")
        observatory.attach(str(tmp_path))
        try:
            p, kernel = _packed()
            check_packed_fleet(p, kernel, hosts=2, segment_iters=2)
        finally:
            observatory.detach()
        prog = json.loads((tmp_path / "progress.json").read_text())
        assert prog["state"] == "done"
        assert prog.get("imbalance") is not None
        assert prog["fleet"]["hosts"] >= 1
        # and the live gauge moved
        g = T._SHARD_IMBALANCE
        assert g.value() >= 1.0

    def test_gauge_set_each_round(self):
        p, kernel = _packed()
        T._SHARD_IMBALANCE.set(-1.0)
        check_packed_fleet(p, kernel, hosts=2, segment_iters=4)
        assert T._SHARD_IMBALANCE.value() >= 1.0


@pytest.mark.chaos
class TestProcHostWorker:
    """One real worker subprocess (the CPU-simulated DCN endpoint):
    the file protocol answers shard segments, and heartbeats flow."""

    def test_single_worker_fleet_completes(self, tmp_path):
        p, kernel = _packed(seed=3, n=100)
        h = fleet.ProcHost("w0", str(tmp_path / "w0"))
        out = check_packed_fleet(p, kernel, hosts=[h],
                                 segment_iters=16)
        assert out["valid"] == check_packed(p, kernel)["valid"]
        hb = fleet.read_heartbeat(str(tmp_path / "w0"))
        assert hb and hb.get("pid")
        assert h.state == "dead"     # stopped at run end

    def test_two_worker_spans_join_one_request_trace(self, tmp_path,
                                                     monkeypatch):
        """Request tracing across the req_N.npz seam: with an ambient
        trace context set, BOTH worker subprocesses' segment spans
        carry the request's trace id, and the stitcher renders them on
        one aligned timeline next to the leader's spans."""
        from jepsen_tpu.obs import fleet as obs_fleet
        from jepsen_tpu.obs import trace as obs_trace
        monkeypatch.delenv("JTPU_TRACE", raising=False)
        p, kernel = _packed(seed=3, n=120)
        tid = obs_trace.new_trace_id()
        tr = obs_trace.tracer()
        tr.attach(str(tmp_path / "trace.jsonl"))
        obs_trace.sync_event()
        try:
            with tr.context(tid):
                with tr.span("serve.request", id="r-fleet"):
                    hosts = [
                        fleet.ProcHost("w0", str(tmp_path / "w0")),
                        fleet.ProcHost("w1", str(tmp_path / "w1"))]
                    out = check_packed_fleet(p, kernel, hosts=hosts,
                                             segment_iters=16)
        finally:
            tr.detach()
        assert out["valid"] == check_packed(p, kernel)["valid"]
        for w in ("w0", "w1"):
            recs, stats = obs_trace.read_trace(
                str(tmp_path / w / "trace.jsonl"))
            assert stats["corrupt"] == 0
            segs = [r for r in recs
                    if r["name"] == "checker.segment"
                    and r.get("trace") == tid]
            assert segs, f"worker {w} emitted no traced segments"
            assert all(r.get("host") == w for r in segs)
            assert any(r["name"] == "trace.sync" for r in recs)
        stitched = obs_fleet.stitch_request(str(tmp_path), tid)
        assert stitched["method"] == "wall-clock"
        seen_hosts = {r.get("host") for r in stitched["records"]}
        assert {"w0", "w1"} <= seen_hosts
        names = {r["name"] for r in stitched["records"]}
        assert "serve.request" in names      # the leader's span too
