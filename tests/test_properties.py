"""Property-based tests (hypothesis): semantic invariances the checkers
must satisfy on EVERY history, not just the golden ones.

The native engine makes these affordable — each verdict is sub-ms, so
hypothesis can push hundreds of structured histories through invariance
checks that would be minutes on the Python search.
"""

import pytest

# hypothesis is an optional dev dependency: without it the module must
# still COLLECT cleanly (a collection error fails tier-1 outright; a
# skip is the contract for missing optional tooling).
hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker.native import available, check_history_native
from jepsen_tpu.checker.wgl import check_model
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister

pytestmark = pytest.mark.skipif(
    not available(), reason="native engine unavailable (no g++?)")


# ---------------------------------------------------------------------------
# History strategy: well-formed concurrent register histories
# ---------------------------------------------------------------------------

N_PROCS = 4
N_VALS = 3


@st.composite
def register_histories(draw, min_ops=2, max_ops=14):
    """A well-formed concurrent history: invokes only on free processes,
    completions only for open invocations, ok/fail/info all possible."""
    n_ops = draw(st.integers(min_ops, max_ops))
    rows, open_ops = [], {}
    t = 0
    budget = n_ops
    while budget > 0 or open_ops:
        can_invoke = budget > 0 and len(open_ops) < N_PROCS
        do_invoke = can_invoke and (not open_ops
                                    or draw(st.booleans()))
        if do_invoke:
            p = draw(st.sampled_from(
                [q for q in range(N_PROCS) if q not in open_ops]))
            f = draw(st.sampled_from(["read", "write", "cas"]))
            if f == "read":
                v = None
            elif f == "write":
                v = draw(st.integers(0, N_VALS - 1))
            else:
                v = (draw(st.integers(0, N_VALS - 1)),
                     draw(st.integers(0, N_VALS - 1)))
            op = Op(type="invoke", f=f, value=v, process=p, time=t)
            rows.append(op)
            open_ops[p] = op
            budget -= 1
        else:
            p = draw(st.sampled_from(sorted(open_ops)))
            inv = open_ops.pop(p)
            kind = draw(st.sampled_from(["ok", "ok", "fail", "info"]))
            v = inv.value
            if kind == "ok" and inv.f == "read":
                v = draw(st.one_of(st.none(),
                                   st.integers(0, N_VALS - 1)))
            rows.append(Op(type=kind, f=inv.f, value=v, process=p,
                           time=t))
        t += 1
    return History(rows)


def verdict(h):
    v = check_history_native(h, CASRegister())["valid"]
    assert v is not UNKNOWN
    return v


# ---------------------------------------------------------------------------
# Invariances
# ---------------------------------------------------------------------------


class TestVerdictInvariances:
    @settings(max_examples=120, deadline=None)
    @given(register_histories())
    def test_native_matches_python_oracle(self, h):
        assert verdict(h) is check_model(h, CASRegister())["valid"]

    @settings(max_examples=80, deadline=None)
    @given(register_histories(), st.randoms())
    def test_process_renaming_preserves_verdict(self, h, rng):
        """Process ids are labels: any bijective renaming leaves the
        real-time partial order (and so the verdict) unchanged."""
        perm = list(range(N_PROCS))
        rng.shuffle(perm)
        h2 = History([o.replace(process=perm[o.process]) for o in h])
        assert verdict(h2) is verdict(h)

    @settings(max_examples=80, deadline=None)
    @given(register_histories())
    def test_removing_failed_pairs_preserves_verdict(self, h):
        """A fail completion asserts the op did NOT happen; the pair
        contributes nothing to the model and drops from the search."""
        if not any(o.is_fail for o in h):
            return
        # drop each fail completion AND its matching invocation
        open_inv = {}
        keep = []
        for o in h:
            if o.is_invoke:
                open_inv[o.process] = o
                keep.append(o)
            elif o.is_fail:
                inv = open_inv.pop(o.process)
                keep.remove(inv)
            else:
                open_inv.pop(o.process, None)
                keep.append(o)
        assert verdict(History(keep)) is verdict(h)

    @settings(max_examples=80, deadline=None)
    @given(register_histories(), st.integers(0, N_VALS - 1))
    def test_adding_crashed_write_keeps_valid_valid(self, h, v):
        """A crashed (info) op MAY be linearized or not — pure extra
        freedom, so it can never invalidate a valid history."""
        if verdict(h) is not True:
            return
        free = [p for p in range(10, 14)]
        extra = Op(type="invoke", f="write", value=v, process=free[0],
                   time=-1)
        crash = Op(type="info", f="write", value=v, process=free[0],
                   time=10**9)
        h2 = History([extra, *h, crash])
        assert verdict(h2) is True

    @settings(max_examples=60, deadline=None)
    @given(register_histories())
    def test_double_history_concatenation_never_unknown(self, h):
        """Sequential self-concatenation (fresh processes for the second
        copy) must still produce a definitive verdict."""
        shift = max((o.time for o in h), default=0) + 1
        second = [o.replace(process=o.process + N_PROCS,
                            time=o.time + shift) for o in h]
        v = check_history_native(History([*h, *second]),
                                 CASRegister())["valid"]
        assert v is not UNKNOWN
