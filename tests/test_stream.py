"""Streaming ingestion + crash-safe online checking (doc/serve.md
"Streaming API", doc/resilience.md "Partial-verdict checkpoints").

Covers the chunked intake contract (sequencing, CRC, duplicate
absorption, bounded reorder, gap 409s), at-least-once delivery
converging on byte-identical history artifacts (including
replay-after-SIGKILL of a half-streamed session), online-vs-offline
verdict identity, fail-fast on an invalid stable prefix, checkpoint
resume at level > 0, the bounded-executor driver mode that feeds
streams, the abandoned-thread leak gauge, and the JTPU_SERVE_STREAM
kill-switch identity contract.
"""

import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from jepsen_tpu import core
from jepsen_tpu import resilience as R
from jepsen_tpu import serve as serve_ns
from jepsen_tpu import stream as stream_ns
from jepsen_tpu.checker import UNKNOWN, check_safe
from jepsen_tpu.checker.wgl import linearizable
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister
from jepsen_tpu.ops.encode import StreamPacker, pack_with_init
from jepsen_tpu.stream import StreamRunner, StreamSession

pytestmark = pytest.mark.serve

#: keys on which a streamed verdict must be indistinguishable from the
#: offline checker's.
_VERDICT_KEYS = ("valid", "levels", "max-linearized-prefix",
                 "final-states", "frontier-op")


def _conc_ops(n, seed, value_base=0, corrupt_at=None):
    """A concurrent register history (4 procs, interleaved invokes);
    ``corrupt_at`` flips that read's value so the history is invalid."""
    rng = random.Random(seed)
    ops, t, pend, val = [], 0, {}, value_base
    reads = 0
    for _ in range(n):
        p = rng.choice((0, 1, 2, 3))
        if p in pend:
            inv = pend.pop(p)
            v = inv["value"]
            if inv["f"] == "read":
                # a read that completes before ANY write was invoked
                # must observe the initial (nil) state, or the history
                # is invalid from op 0
                v = val if val != value_base else None
                reads += 1
                if corrupt_at is not None and reads == corrupt_at:
                    v = val + 10_000   # never written: unlinearizable
            ops.append({"process": p, "type": "ok", "f": inv["f"],
                        "value": v, "time": t})
        else:
            f = rng.choice(("write", "read"))
            v = val + 1 if f == "write" else None
            if f == "write":
                val += 1
            inv = {"process": p, "type": "invoke", "f": f, "value": v,
                   "time": t}
            ops.append(inv)
            pend[p] = inv
        t += 1
    for p, inv in sorted(pend.items()):
        if inv["f"] == "read":
            v = val if val != value_base else None
        else:
            v = inv["value"]
        ops.append({"process": p, "type": "ok", "f": inv["f"],
                    "value": v, "time": t})
        t += 1
    return ops


def _offline(ops):
    return check_safe(linearizable(CASRegister(), backend="tpu"),
                      {"name": "stream-offline"},
                      History.of([Op.from_dict(d) for d in ops]))


def _chunks(ops, size):
    return [ops[i:i + size] for i in range(0, len(ops), size)]


def _session(tmp_path, sid="s1", **kw):
    return StreamSession(sid, "t", "cas-register", str(tmp_path), **kw)


def _runner(session, **kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("segment_iters", 64)
    r = StreamRunner(session, CASRegister(), **kw)
    session.runner = r
    r.start()
    return r


def _wait_done(session, runner=None, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with session.lock:
            if session.state == "done" and session.result is not None:
                if runner is not None:
                    runner.join(timeout=10)
                return session.result
        time.sleep(0.02)
    raise AssertionError(f"stream never finished: {session.status()}")


def _stop(runner):
    runner.stop()
    runner.join(timeout=10)


# ---------------------------------------------------------------------------
# StreamPacker: append-mode packing is byte-identical to pack_history
# ---------------------------------------------------------------------------


_PACK_COLS = ("f", "v1", "v2", "inv", "ret", "process")


class TestStreamPacker:
    def test_close_matches_offline_pack(self):
        ops = _conc_ops(120, 11)
        packer = _fresh_packer()
        packer.feed_ops(ops)
        online = packer.close()
        offline, _ = _packed_offline(ops)
        for name in _PACK_COLS:
            assert np.array_equal(getattr(online, name),
                                  getattr(offline, name)), name
        assert online.n_required == offline.n_required
        assert online.init_state == offline.init_state

    def test_stable_prefix_extends_monotonically(self):
        """Packed columns of a longer stable prefix exactly extend the
        shorter one — the invariant that lets the carry survive
        barriers."""
        ops = _conc_ops(80, 12)
        packer = _fresh_packer()
        prev = None
        for op in ops:
            packer.feed_ops([op])
            p = packer.stable_packed()
            if prev is not None:
                assert p.n >= prev.n
                if prev.n:
                    for name in _PACK_COLS:
                        a = np.asarray(getattr(p, name))[:prev.n]
                        b = np.asarray(getattr(prev, name))[:prev.n]
                        assert np.array_equal(a, b), name
            prev = p
        final = packer.close()
        offline, _ = _packed_offline(ops)
        assert final.n == offline.n

    def test_watermark_pinned_by_open_invoke(self):
        packer = _fresh_packer()
        packer.feed_ops([{"type": "invoke", "f": "write",
                         "value": 1, "process": 0, "time": 0}])
        assert packer.watermark == 0
        packer.feed_ops([{"type": "ok", "f": "write",
                         "value": 1, "process": 0, "time": 1}])
        assert packer.watermark == 2


def _fresh_packer():
    from jepsen_tpu.models.core import kernel_spec_for
    from jepsen_tpu.ops.encode import _Interner
    model = CASRegister()
    kernel = kernel_spec_for(model)
    intern = _Interner()
    init = (kernel.pack_init(model, intern.id)
            if kernel.pack_init is not None else kernel.init_state)
    return StreamPacker(kernel, init_state=init, intern=intern)


def _packed_offline(ops):
    return pack_with_init(
        History.of([Op.from_dict(d) for d in ops]), CASRegister())


# ---------------------------------------------------------------------------
# Intake: at-least-once delivery converges on identical artifacts
# ---------------------------------------------------------------------------


class TestIntakeAtLeastOnce:
    def test_repost_of_acked_chunk_absorbed_without_rejournal(
            self, tmp_path):
        s = _session(tmp_path)
        chunk = _conc_ops(8, 1)
        code, body = s.append(0, chunk, stream_ns.chunk_crc(chunk))
        assert code == 202 and body["need"] == 1
        wal_before = open(
            os.path.join(s.dir, stream_ns.WAL_NAME), "rb").read()
        code, body = s.append(0, chunk, stream_ns.chunk_crc(chunk))
        assert code == 202 and body["duplicate"] is True
        assert len(s.ops) == len(chunk)
        wal_after = open(
            os.path.join(s.dir, stream_ns.WAL_NAME), "rb").read()
        assert wal_after == wal_before   # dup never re-journaled
        s.stop_wal()

    def test_out_of_order_buffers_then_drains_in_sequence(self, tmp_path):
        s = _session(tmp_path)
        ops = _conc_ops(24, 2)
        c = _chunks(ops, 8)
        code, body = s.append(1, c[1])
        assert code == 202 and body["buffered"] is True
        assert s.ops == []               # nothing admitted yet
        code, body = s.append(2, c[2])
        assert code == 202 and body["buffered"] is True
        code, body = s.append(0, c[0])
        assert code == 202 and body["need"] == 3
        assert s.ops == c[0] + c[1] + c[2]   # drained in sequence order
        s.stop_wal()

    def test_gap_beyond_reorder_window_409_with_need(self, tmp_path):
        s = _session(tmp_path, reorder_max=2)
        code, body = s.append(5, [])
        assert code == 409 and body["error"] == "gap"
        assert body["need"] == 0
        s.stop_wal()

    def test_crc_mismatch_400(self, tmp_path):
        s = _session(tmp_path)
        code, body = s.append(0, _conc_ops(4, 3), "deadbeef")
        assert code == 400 and body["error"] == "crc-mismatch"
        s.stop_wal()

    def test_close_refuses_holes(self, tmp_path):
        s = _session(tmp_path)
        c = _chunks(_conc_ops(24, 4), 8)
        s.append(0, c[0])
        s.append(2, c[2])                # 1 is missing, buffered
        code, body = s.close(3)
        assert code == 409 and body["error"] == "gap"
        assert body["need"] == 1
        s.append(1, c[1])
        code, body = s.close(3)
        assert code == 200 and body["state"] == "closed"
        s.stop_wal()

    def test_duplicate_after_close_still_202(self, tmp_path):
        s = _session(tmp_path)
        chunk = _conc_ops(8, 5)
        s.append(0, chunk)
        s.close(1)
        code, body = s.append(0, chunk)
        assert code == 202 and body["duplicate"] is True
        assert body["state"] == "closed"
        s.stop_wal()

    def test_replay_after_kill_yields_byte_identical_history(
            self, tmp_path):
        """SIGKILL between chunks: the WAL replay reconstructs the
        session — including its reorder buffer — and the sealed
        history.json is byte-for-byte what an unkilled stream writes."""
        ops = _conc_ops(60, 6)
        c = _chunks(ops, 10)
        # reference: a clean uninterrupted stream
        ref = _session(tmp_path / "clean", sid="ref")
        for i, ch in enumerate(c):
            ref.append(i, ch)
        ref.close(len(c))
        ref.stop_wal()
        ref_bytes = open(
            os.path.join(ref.dir, stream_ns.HISTORY_NAME), "rb").read()
        # the killed stream: half delivered, one chunk buffered out of
        # order, then the process "dies" (WAL handle simply abandoned)
        s = _session(tmp_path / "killed", sid="ref")
        s.append(0, c[0])
        s.append(1, c[1])
        s.append(3, c[3])                # buffered: 2 still missing
        s.stop_wal()                     # SIGKILL
        s2 = StreamSession.replay(s.dir, str(tmp_path / "killed"))
        assert s2 is not None and s2.state == "open"
        assert s2.ops == c[0] + c[1]
        assert 3 in s2.reorder           # buffer survived the crash
        # the client's at-least-once retry re-sends everything unacked
        code, body = s2.append(1, c[1])
        assert code == 202 and body["duplicate"] is True
        for i in range(2, len(c)):       # 3 dups against the buffer
            code, _ = s2.append(i, c[i])
            assert code == 202
        code, body = s2.close(len(c))
        assert code == 200
        s2.stop_wal()
        killed_bytes = open(
            os.path.join(s2.dir, stream_ns.HISTORY_NAME), "rb").read()
        assert killed_bytes == ref_bytes

    def test_replay_of_sealed_session_rewrites_identical_history(
            self, tmp_path):
        ops = _conc_ops(40, 7)
        s = _session(tmp_path)
        c = _chunks(ops, 10)
        for i, ch in enumerate(c):
            s.append(i, ch)
        s.close(len(c))
        s.stop_wal()
        hpath = os.path.join(s.dir, stream_ns.HISTORY_NAME)
        ref = open(hpath, "rb").read()
        os.unlink(hpath)                 # crashed before rename landed
        s2 = StreamSession.replay(s.dir, str(tmp_path))
        assert s2.state == "closed"
        s2.stop_wal()
        assert open(hpath, "rb").read() == ref


# ---------------------------------------------------------------------------
# Online checking: verdict identity, fail-fast, crash resume
# ---------------------------------------------------------------------------


class TestOnlineVerdict:
    def test_streamed_verdict_matches_offline_with_dup_and_reorder(
            self, tmp_path):
        ops = _conc_ops(240, 21)
        c = _chunks(ops, 24)
        s = _session(tmp_path)
        r = _runner(s)
        try:
            for i, ch in enumerate(c):
                if i == 3:               # out-of-order pair
                    s.append(4, c[4])
                    s.append(3, c[3])
                    s.append(4, c[4])    # and a duplicate
                    continue
                if i == 4:
                    continue
                s.append(i, ch, stream_ns.chunk_crc(ch))
            code, _ = s.close(len(c))
            assert code == 200
            result = _wait_done(s, r)
        finally:
            _stop(r)
        offline = _offline(ops)
        for key in _VERDICT_KEYS:
            assert result.get(key) == offline.get(key), key
        st = result["stream"]
        assert st["ops"] == len(ops)
        assert st["dup-chunks"] >= 1 and st["reordered"] >= 1
        assert st["failed-fast"] is False
        assert st["watermark"] == len(ops)

    def test_failfast_refutes_invalid_prefix_while_stream_open(
            self, tmp_path):
        """An invalid stable prefix renders the verdict BEFORE close:
        the session jumps open -> done and later appends answer 409
        stream-failed."""
        ops = _conc_ops(200, 22, corrupt_at=3)
        offline = _offline(ops)
        assert offline["valid"] is False
        c = _chunks(ops, 20)
        s = _session(tmp_path)
        r = _runner(s, segment_iters=16)
        try:
            # hold back the last chunk: the refutation must come from
            # the invalid stable prefix alone, with the stream open
            sent = 0
            while sent < len(c) - 1:
                code, body = s.append(sent, c[sent])
                if code == 409 and body["error"] == "stream-failed":
                    break
                assert code == 202
                sent += 1
            result = _wait_done(s, r)
        finally:
            _stop(r)
        assert result["valid"] is False
        assert result["stream"]["failed-fast"] is True
        # refuted strictly mid-stream: the tail never arrived
        assert result["stream"]["watermark"] < len(ops)
        code, body = s.append(len(c) - 1, c[-1])
        assert code == 409 and body["error"] == "stream-failed"

    def test_trivial_empty_stream_is_valid(self, tmp_path):
        s = _session(tmp_path)
        r = _runner(s)
        try:
            s.close(0)
            result = _wait_done(s, r)
        finally:
            _stop(r)
        assert result["valid"] is True


class TestCrashResume:
    def test_resume_from_checkpoint_continues_above_level_zero(
            self, tmp_path):
        """The crash-safety headline: kill the daemon mid-stream, replay
        the WAL, and the search resumes from the partial-verdict
        checkpoint — never level 0 — with the final verdict identical
        to offline."""
        ops = _conc_ops(320, 23)
        c = _chunks(ops, 16)
        s = _session(tmp_path)
        r = _runner(s, segment_iters=1)  # checkpoint every level
        cp_path = os.path.join(s.dir, stream_ns.CHECKPOINT_NAME)
        try:
            for i, ch in enumerate(c):
                s.append(i, ch)
            deadline = time.monotonic() + 60
            level = 0
            while time.monotonic() < deadline:
                if os.path.exists(cp_path):
                    try:
                        level = R.Checkpoint.load(cp_path).level
                    except Exception:  # noqa: BLE001 — mid-save race
                        level = 0
                    if level > 0:
                        break
                time.sleep(0.02)
            assert level > 0, "no mid-stream checkpoint ever landed"
        finally:
            _stop(r)                     # SIGKILL stand-in
        s.stop_wal()
        # next daemon incarnation: WAL replay + checkpoint resume
        s2 = StreamSession.replay(s.dir, str(tmp_path))
        assert s2 is not None and s2.state == "open"
        assert s2.ops == ops
        r2 = _runner(s2, segment_iters=64)
        try:
            code, _ = s2.close(len(c))
            assert code == 200
            result = _wait_done(s2, r2)
        finally:
            _stop(r2)
        assert result["stream"].get("resume-level", 0) > 0
        offline = _offline(ops)
        for key in _VERDICT_KEYS:
            assert result.get(key) == offline.get(key), key

    def test_corrupt_checkpoint_starts_fresh_not_crashed(self, tmp_path):
        ops = _conc_ops(80, 24)
        s = _session(tmp_path)
        with open(os.path.join(s.dir, stream_ns.CHECKPOINT_NAME),
                  "wb") as f:
            f.write(b"not an npz")
        r = _runner(s)
        try:
            c = _chunks(ops, 20)
            for i, ch in enumerate(c):
                s.append(i, ch)
            s.close(len(c))
            result = _wait_done(s, r)
        finally:
            _stop(r)
        assert result["valid"] == _offline(ops)["valid"]
        assert "resume-level" not in result["stream"]


# ---------------------------------------------------------------------------
# Daemon integration: admission, replay-on-restart, progress keys
# ---------------------------------------------------------------------------


def _daemon(tmp_path, start=True, **cfg):
    cfg.setdefault("root", str(tmp_path / "serve"))
    cfg.setdefault("backend", "tpu")
    d = serve_ns.CheckDaemon(serve_ns.ServeConfig(**cfg))
    if start:
        d.start()
    return d


class TestDaemonStreaming:
    def test_open_feed_close_verdict_and_observability(self, tmp_path):
        ops = _conc_ops(160, 31)
        c = _chunks(ops, 20)
        d = _daemon(tmp_path)
        try:
            code, body, _ = d.stream_open({"tenant": "t1",
                                           "model": "cas-register"})
            assert code == 202 and body["state"] == "open"
            sid = body["id"]
            for i, ch in enumerate(c):
                code, body, _ = d.stream_append(
                    sid, {"seq": i, "ops": ch,
                          "crc": stream_ns.chunk_crc(ch)})
                assert code == 202
            code, body, _ = d.stream_close(sid, {"chunks": len(c)})
            assert code == 200
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                doc = d.stream_status(sid)
                if doc["state"] == "done" and doc.get("result"):
                    break
                time.sleep(0.05)
            assert doc["state"] == "done"
            offline = _offline(ops)
            for key in _VERDICT_KEYS:
                assert doc["result"].get(key) == offline.get(key), key
            hz = d.healthz()
            assert hz["streams"]["sessions"] >= 1
            d._publish(force=True)
            with open(os.path.join(d.config.root,
                                   serve_ns.PROGRESS_NAME)) as f:
                prog = json.load(f)["serve"]
            assert "streams" in prog and "stream-ops" in prog
        finally:
            d.stop()

    def test_unknown_model_400_and_unknown_stream_404(self, tmp_path):
        d = _daemon(tmp_path, start=False)
        try:
            code, body, _ = d.stream_open({"model": "no-such-model"})
            assert code == 400
            code, body, _ = d.stream_append("nope", {"seq": 0, "ops": []})
            assert code == 404
        finally:
            d.stop()

    def test_stream_quota_429_with_retry_after(self, tmp_path):
        d = _daemon(tmp_path, start=False, stream_max=1)
        try:
            code, body, _ = d.stream_open({"model": "cas-register"})
            assert code == 202
            code, body, hdrs = d.stream_open({"model": "cas-register"})
            assert code == 429 and body["error"] == "stream-quota"
            assert int(hdrs["Retry-After"]) >= 1
        finally:
            d.stop()

    def test_stream_quota_has_no_toctou_window(self, tmp_path,
                                               monkeypatch):
        """Two concurrent opens racing at stream_max - 1 live sessions
        must not BOTH be admitted. The quota check and the slot
        reservation are one critical section; a session ctor stalled
        mid-construction (I/O) still holds its reserved slot, so the
        second open sees the quota as full and answers 429."""
        entered, release = threading.Event(), threading.Event()
        real_session = stream_ns.StreamSession

        class StalledSession(real_session):
            def __init__(self, *a, **kw):
                entered.set()
                assert release.wait(30), "test never released the ctor"
                super().__init__(*a, **kw)

        monkeypatch.setattr(stream_ns, "StreamSession", StalledSession)
        d = _daemon(tmp_path, start=False, stream_max=1)
        first = {}

        def open_first():
            first["resp"] = d.stream_open({"model": "cas-register"})

        t = threading.Thread(target=open_first, daemon=True)
        try:
            t.start()
            assert entered.wait(30), "first open never reached the ctor"
            # the first open is parked INSIDE session construction:
            # its slot is reserved but the session object doesn't
            # exist yet — exactly the window the old split check raced
            code, body, _ = d.stream_open({"model": "cas-register"})
            assert code == 429 and body["error"] == "stream-quota"
            # the daemon stays serviceable around the placeholder
            assert d.healthz()["ok"] is True
        finally:
            release.set()
            t.join(30)
            d.stop()
        assert not t.is_alive()
        code, body, _ = first["resp"]
        assert code == 202 and body["state"] == "open"

    def test_backpressure_429_when_intake_outruns_checker(self, tmp_path):
        d = _daemon(tmp_path, start=False, stream_buffer_ops=10)
        try:
            code, body, _ = d.stream_open({"model": "cas-register"})
            sid = body["id"]
            # no runner progress: lag == accepted ops
            sess = d._stream_session(sid)
            sess.runner and _stop(sess.runner)
            ops = _conc_ops(40, 32)
            code, body, hdrs = d.stream_append(
                sid, {"seq": 0, "ops": ops})
            assert code == 202
            code, body, hdrs = d.stream_append(
                sid, {"seq": 1, "ops": ops})
            assert code == 429 and body["error"] == "backpressure"
            assert int(hdrs["Retry-After"]) >= 1
        finally:
            d.stop()

    def test_daemon_restart_replays_open_stream_and_finishes(
            self, tmp_path):
        ops = _conc_ops(200, 33)
        c = _chunks(ops, 20)
        d1 = _daemon(tmp_path)
        try:
            code, body, _ = d1.stream_open({"model": "cas-register"})
            sid = body["id"]
            for i in range(5):           # half the stream, then "kill"
                code, _, _ = d1.stream_append(sid, {"seq": i,
                                                    "ops": c[i]})
                assert code == 202
        finally:
            d1.stop()
        d2 = _daemon(tmp_path)
        try:
            doc = d2.stream_status(sid)
            assert doc is not None and doc["state"] == "open"
            assert doc["ops"] == 100     # replayed intake survived
            for i in range(5, len(c)):
                code, body, _ = d2.stream_append(sid, {"seq": i,
                                                       "ops": c[i]})
                assert code == 202
            code, _, _ = d2.stream_close(sid, {"chunks": len(c)})
            assert code == 200
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                doc = d2.stream_status(sid)
                if doc["state"] == "done" and doc.get("result"):
                    break
                time.sleep(0.05)
            offline = _offline(ops)
            for key in _VERDICT_KEYS:
                assert doc["result"].get(key) == offline.get(key), key
        finally:
            d2.stop()


# ---------------------------------------------------------------------------
# HTTP routes
# ---------------------------------------------------------------------------


def _post(port, path, doc):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode() if doc is not None else b"",
        method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


class TestStreamHTTP:
    def test_stream_routes_end_to_end(self, tmp_path):
        import urllib.request
        ops = _conc_ops(120, 41)
        c = _chunks(ops, 30)
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu")
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0,
            store_root=str(tmp_path / "store"))
        port = server.server_port
        try:
            code, body, _ = _post(port, "/stream",
                                  {"model": "cas-register"})
            assert code == 202
            sid = body["id"]
            for i, ch in enumerate(c):
                code, body, _ = _post(
                    port, f"/stream/{sid}/ops",
                    {"seq": i, "ops": ch,
                     "crc": stream_ns.chunk_crc(ch)})
                assert code == 202
            # a gap past the reorder window resyncs the client
            code, body, _ = _post(port, f"/stream/{sid}/ops",
                                  {"seq": 500, "ops": []})
            assert code == 409 and body["need"] == len(c)
            code, body, _ = _post(port, f"/stream/{sid}/close",
                                  {"chunks": len(c)})
            assert code == 200
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/stream/{sid}") as r:
                    doc = json.load(r)
                if doc["state"] == "done" and doc.get("result"):
                    break
                time.sleep(0.05)
            assert doc["result"]["valid"] == _offline(ops)["valid"]
        finally:
            server.shutdown()
            daemon.stop()


# ---------------------------------------------------------------------------
# Kill switch: JTPU_SERVE_STREAM=0 leaves the daemon byte-identical
# ---------------------------------------------------------------------------


class TestStreamKillSwitch:
    def test_off_daemon_has_no_streams_anywhere(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("JTPU_SERVE_STREAM", "0")
        d = _daemon(tmp_path)
        try:
            assert d.config.stream_on is False
            assert d._streams is None
            hz = d.healthz()
            assert "streams" not in hz
            d._publish(force=True)
            with open(os.path.join(d.config.root,
                                   serve_ns.PROGRESS_NAME)) as f:
                prog = json.load(f)["serve"]
            for key in ("streams", "stream-ops", "stream-checked",
                        "stream-lag"):
                assert key not in prog
            assert not os.path.isdir(
                os.path.join(d.config.root, "streams"))
        finally:
            d.stop()

    def test_off_http_routes_404(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JTPU_SERVE_STREAM", "0")
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"))
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0,
            store_root=str(tmp_path / "store"))
        try:
            code, _, _ = _post(server.server_port, "/stream",
                               {"model": "cas-register"})
            assert code == 404
        finally:
            server.shutdown()
            daemon.stop()

    def test_off_never_imports_stream_module(self, tmp_path):
        """The lazy-import discipline, checked in a clean interpreter:
        with the kill switch thrown, constructing + starting + stopping
        the daemon never imports jepsen_tpu.stream, so none of its
        metric names register."""
        code = subprocess.run(
            [sys.executable, "-c", (
                "import sys\n"
                "from jepsen_tpu import serve\n"
                "d = serve.CheckDaemon(serve.ServeConfig(root=%r))\n"
                "d.start(); d.stop()\n"
                "assert 'jepsen_tpu.stream' not in sys.modules\n"
            ) % str(tmp_path / "serve")],
            env={**os.environ, "JTPU_SERVE_STREAM": "0",
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=180)
        assert code.returncode == 0, code.stdout + code.stderr


# ---------------------------------------------------------------------------
# Bounded-executor driver mode (test["driver-threads"])
# ---------------------------------------------------------------------------


class _EchoClient:
    def __init__(self):
        self.threads = set()
        self.lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        with self.lock:
            self.threads.add(threading.current_thread().name)
        return op.replace(type="ok")

    def close(self, test):
        pass


class _ScriptGen:
    """Hands each worker process a fixed number of ops; nothing for the
    nemesis."""

    def __init__(self, per_process):
        self.left = dict(per_process)
        self.lock = threading.Lock()

    def op(self, test, process):
        from jepsen_tpu.history import NEMESIS
        if process == NEMESIS:
            return None
        with self.lock:
            thread = process % test["concurrency"]
            if self.left.get(thread, 0) <= 0:
                return None
            self.left[thread] -= 1
        return Op(type="invoke", f="w", value=None, process=process)


class TestBoundedDriver:
    def test_k_pool_threads_drive_n_processes(self):
        n, k, per = 12, 3, 5
        client = _EchoClient()
        test = {"name": "bounded", "client": client,
                "generator": _ScriptGen({i: per for i in range(n)}),
                "concurrency": n, "driver-threads": k, "nodes": ["a"]}
        h = core._run_case(test)
        ops = [o for o in h if isinstance(o.process, int)]
        invs = [o for o in ops if o.type == "invoke"]
        assert len(invs) == n * per
        assert len({o.process for o in invs}) == n
        # every invoke ran on a pool thread, and only k of them existed
        assert client.threads
        assert all(t.startswith("jepsen-driver-") for t in client.threads)
        assert len(client.threads) <= k
        # per-process histories stay strictly invoke/ok alternating
        for p in {o.process for o in invs}:
            seq = [o.type for o in ops if o.process == p]
            assert seq == ["invoke", "ok"] * (len(seq) // 2)

    def test_info_reincarnates_process_in_bounded_mode(self):
        n = 4

        class CrashOnce(_EchoClient):
            def __init__(self):
                super().__init__()
                self.crashed = False

            def invoke(self, test, op):
                with self.lock:
                    if not self.crashed and op.process == 1:
                        self.crashed = True
                        raise RuntimeError("connection torn")
                return op.replace(type="ok")

        test = {"name": "bounded-crash", "client": CrashOnce(),
                "generator": _ScriptGen({i: 3 for i in range(n)}),
                "concurrency": n, "driver-threads": 2, "nodes": ["a"]}
        h = core._run_case(test)
        procs = {o.process for o in h if isinstance(o.process, int)}
        assert 1 + n in procs            # reincarnated as p + concurrency
        infos = [o for o in h if o.type == "info"
                 and isinstance(o.process, int)]
        assert len(infos) == 1 and infos[0].process == 1

    def test_worker_error_propagates_and_stops_pool(self):
        """A generator error (outside the info/reincarnation contract)
        stops the pool and re-raises — the threaded mode's crash
        propagation."""
        class BadGen(_ScriptGen):
            def op(self, test, process):
                out = super().op(test, process)
                if out is not None and process == 2:
                    raise RuntimeError("generator blew up")
                return out

        test = {"name": "bounded-bad", "client": _EchoClient(),
                "generator": BadGen({i: 2 for i in range(4)}),
                "concurrency": 4, "driver-threads": 2, "nodes": ["a"]}
        with pytest.raises(RuntimeError, match="generator blew up"):
            core._run_case(test)

    def test_full_thread_mode_untouched_without_flag(self):
        n = 3
        client = _EchoClient()
        test = {"name": "threaded", "client": client,
                "generator": _ScriptGen({i: 2 for i in range(n)}),
                "concurrency": n, "nodes": ["a"]}
        h = core._run_case(test)
        invs = [o for o in h if o.type == "invoke"
                and isinstance(o.process, int)]
        assert len(invs) == n * 2
        assert all(t.startswith("jepsen-worker-") for t in client.threads)


# ---------------------------------------------------------------------------
# Abandoned-thread leak gauge (with_op_timeout)
# ---------------------------------------------------------------------------


class TestAbandonedThreads:
    def test_timeout_counts_the_leaked_thread(self):
        release = threading.Event()
        before = core.abandoned_threads()
        with pytest.raises(core.OpTimeout):
            core.with_op_timeout(0.05, release.wait)
        assert core.abandoned_threads() == before + 1
        release.set()                    # let the leak drain

    def test_analyze_prints_leaked_threads_line(self, tmp_path):
        import contextlib
        import io
        from jepsen_tpu import cli
        release = threading.Event()
        with pytest.raises(core.OpTimeout):
            core.with_op_timeout(0.05, release.wait)
        d = tmp_path / "run"
        d.mkdir()
        h = History.of([
            Op(type="invoke", f="write", value=1, process=0, time=0),
            Op(type="ok", f="write", value=1, process=0, time=1),
        ]).index()
        (d / "history.jsonl").write_text(h.to_jsonl() + "\n")
        (d / "test.json").write_text('{"name": "t"}')
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run(cli.default_commands(),
                         ["analyze", "--store", str(d)])
        release.set()
        assert rc == cli.OK
        assert "# leaked-threads:" in buf.getvalue()
