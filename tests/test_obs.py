"""Observability subsystem (jepsen_tpu.obs): span tracer semantics,
metrics registry math, export formats, run artifacts, the JTPU_TRACE
kill switch, the /metrics + /live endpoints, and the search
observatory (live progress, device memory accounting, XLA cost
accounting). Tier-1 under the ``obs`` marker (doc/observability.md is
the operator view)."""

import json
import os
import threading
import urllib.request

import pytest

from jepsen_tpu import obs
from jepsen_tpu.obs import devices as obs_devices
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import observatory as obs_observatory
from jepsen_tpu.obs import trace as obs_trace

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_parents_and_order(self):
        tr = obs_trace.Tracer()
        with tr.span("outer", layer="core"):
            with tr.span("inner"):
                pass
            with tr.span("inner2"):
                pass
        recs = tr.spans()
        assert [r["name"] for r in recs] == ["inner", "inner2", "outer"]
        outer = recs[2]
        assert "pid" not in outer and outer["layer"] == "core"
        assert recs[0]["pid"] == outer["sid"]
        assert recs[1]["pid"] == outer["sid"]
        assert all(r["dur"] >= 0 and r["ts"] >= 0 for r in recs)

    def test_name_attr_does_not_collide(self):
        # span("x", name=...) must record name as an attribute, not
        # clobber the span's own name (positional-only parameter)
        tr = obs_trace.Tracer()
        with tr.span("core.run", name="etcd-cas"):
            pass
        (r,) = tr.spans()
        assert r["name"] == "core.run"

    def test_exception_recorded_and_propagated(self):
        tr = obs_trace.Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("kaput")
        (r,) = tr.spans()
        assert r["error"] == "ValueError: kaput"

    def test_threads_do_not_cross_parent(self):
        tr = obs_trace.Tracer()
        done = threading.Event()

        def child():
            with tr.span("child-span"):
                pass
            done.set()

        with tr.span("parent-span"):
            t = threading.Thread(target=child)
            t.start()
            t.join()
        assert done.is_set()
        by_name = {r["name"]: r for r in tr.spans()}
        # the other thread's span is a root: no cross-thread parent
        assert "pid" not in by_name["child-span"]
        assert by_name["child-span"]["tid"] != \
            by_name["parent-span"]["tid"]

    def test_ring_is_bounded(self):
        tr = obs_trace.Tracer(ring=16)
        for i in range(100):
            with tr.span(f"s{i}"):
                pass
        recs = tr.spans()
        assert len(recs) == 16
        assert recs[-1]["name"] == "s99"
        assert tr.recorded == 100

    def test_event_is_instant(self):
        tr = obs_trace.Tracer()
        tr.event("search.oom", outcome="pool-halved")
        (r,) = tr.spans()
        assert r["dur"] == 0 and r["outcome"] == "pool-halved"

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("JTPU_TRACE", "0")
        before = obs.tracer().recorded
        sp = obs.span("nope", x=1)
        assert sp is obs_trace.NOOP_SPAN
        with sp:
            sp.set(y=2)
        obs.event("nope-either")
        assert obs.tracer().recorded == before
        monkeypatch.setenv("JTPU_TRACE", "1")
        with obs.span("yes"):
            pass
        assert obs.tracer().recorded == before + 1


class TestTraceArtifact:
    def test_sink_and_tail_tolerant_read(self, tmp_path):
        p = str(tmp_path / "trace.jsonl")
        tr = obs_trace.Tracer(path=p)
        with tr.span("a"):
            with tr.span("b"):
                pass
        tr.detach()
        recs, stats = obs_trace.read_trace(p)
        assert stats == {"spans": 2, "torn": 0, "corrupt": 0,
                         "traces": 0}
        assert [r["name"] for r in recs] == ["b", "a"]
        # a SIGKILL mid-write leaves a torn, unterminated tail: dropped
        # silently, earlier records intact
        with open(p, "ab") as f:
            f.write(b'{"name": "torn", "ts": 12')
        recs, stats = obs_trace.read_trace(p)
        assert stats == {"spans": 2, "torn": 1, "corrupt": 0,
                         "traces": 0}
        # a corrupt MIDDLE line (terminated) counts as corruption
        with open(p, "ab") as f:
            f.write(b'3, "dur": 0}garbage\n')
        with tr.span("c"):
            pass  # ring only; sink detached
        recs, stats = obs_trace.read_trace(p)
        assert stats["corrupt"] == 1 and stats["spans"] == 2

    def test_chrome_export_matches_golden(self):
        records = [
            {"name": "core.run", "ts": 1000, "dur": 9000, "tid": 7,
             "sid": 1, "name_attr": "demo"},
            {"name": "checker.segment", "ts": 2000, "dur": 3000,
             "tid": 7, "sid": 2, "pid": 1, "phase": "compile",
             "level": 0},
            {"name": "search.oom", "ts": 6000, "dur": 0, "tid": 7,
             "sid": 3, "pid": 1, "outcome": "pool-halved-to-64"},
        ]
        golden_path = os.path.join(REPO, "tests", "fixtures", "obs",
                                   "chrome_golden.json")
        with open(golden_path) as f:
            golden = json.load(f)
        assert obs_trace.to_chrome(records,
                                   process_name="golden") == golden
        # structural invariants Perfetto relies on: complete events
        # carry dur, instants carry a scope, ts is microseconds
        evs = golden["traceEvents"]
        assert evs[1]["ph"] == "X" and evs[1]["ts"] == 1.0
        assert evs[3]["ph"] == "i" and evs[3]["s"] == "t"

    def test_summarize(self):
        recs = [{"name": "a", "ts": 0, "dur": 5},
                {"name": "a", "ts": 1, "dur": 7},
                {"name": "b", "ts": 2, "dur": 1}]
        s = obs_trace.summarize(recs)
        assert s["a"] == {"count": 2, "total-ns": 12, "max-ns": 7}
        assert list(s) == ["a", "b"]


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


class TestMetrics:
    def _registry(self):
        return obs_metrics.Registry()

    def test_counter_labels(self):
        reg = self._registry()
        c = reg.counter("jtpu_x_total", "things")
        c.inc()
        c.inc(2, f="read")
        c.inc(3, f="read")
        assert c.value() == 1
        assert c.value(f="read") == 5

    def test_gauge_set_max(self):
        reg = self._registry()
        g = reg.gauge("jtpu_hwm")
        g.set_max(4)
        g.set_max(2)
        assert g.value() == 4
        g.set(1)
        assert g.value() == 1

    def test_histogram_bucket_math(self):
        reg = self._registry()
        h = reg.histogram("jtpu_lat_seconds", "l",
                          buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 0.5, 50.0):
            h.observe(v)
        s = h.series()
        # non-cumulative internal tallies: <=0.01, <=0.1, <=1.0, +Inf
        assert s["buckets"] == [1, 2, 1, 1]
        assert s["count"] == 5
        assert abs(s["sum"] - 50.605) < 1e-9
        # exposition is cumulative
        text = "\n".join(h.expose())
        assert 'le="0.01"} 1' in text
        assert 'le="0.1"} 3' in text
        assert 'le="1"} 4' in text
        assert 'le="+Inf"} 5' in text
        assert "jtpu_lat_seconds_count 5" in text

    def test_prometheus_exposition_format(self):
        reg = self._registry()
        reg.counter("jtpu_a_total", "a help").inc(2, f='with"quote',
                                                  g="line\nbreak")
        reg.gauge("jtpu_b", "b help").set(1.5)
        text = reg.to_prometheus()
        assert text.endswith("\n")
        assert "# HELP jtpu_a_total a help" in text
        assert "# TYPE jtpu_a_total counter" in text
        assert "# TYPE jtpu_b gauge" in text
        # label escaping per the exposition spec
        assert 'f="with\\"quote"' in text
        assert 'g="line\\nbreak"' in text
        assert "jtpu_b 1.5" in text

    def test_type_conflict_raises(self):
        reg = self._registry()
        reg.counter("jtpu_dup")
        with pytest.raises(TypeError):
            reg.gauge("jtpu_dup")

    def test_snapshot_roundtrips_as_json(self, tmp_path):
        reg = self._registry()
        reg.counter("jtpu_c_total").inc(4)
        reg.histogram("jtpu_h_seconds", buckets=(1.0,)).observe(0.5)
        doc = json.loads(json.dumps(reg.snapshot()))
        assert doc["jtpu_c_total"]["series"][""] == 4
        assert doc["jtpu_h_seconds"]["series"][""]["count"] == 1


# ---------------------------------------------------------------------------
# Instrumented layers
# ---------------------------------------------------------------------------


class TestInstrumentation:
    def test_wal_fsync_histogram(self, tmp_path):
        from jepsen_tpu import journal
        from jepsen_tpu.history import Op
        h = obs_metrics.REGISTRY.histogram("jtpu_wal_fsync_seconds")
        before = (h.series(sync="op") or {"count": 0})["count"]
        j = journal.Journal(str(tmp_path / "history.wal"), sync="op")
        for i in range(3):
            j.append(Op(type="invoke", f="read", process=i))
        j.close()
        after = h.series(sync="op")["count"]
        assert after - before == 3
        b = obs_metrics.REGISTRY.histogram("jtpu_wal_batch_records")
        assert b.series() and b.series()["count"] > 0

    def test_supervised_search_surfaces_telemetry(self):
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.ops.encode import pack_with_init
        from jepsen_tpu.resilience import supervised_check_packed
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=3)
        p, kernel = pack_with_init(h, CASRegister())
        before = obs.tracer().recorded
        r = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                    segment_iters=8)
        assert r["valid"] is True
        assert r["segments"] >= 1
        assert set(r["device-s"]) == {"compile", "execute"}
        assert len(r["segment-levels"]) == r["segments"]
        assert sum(r["segment-levels"]) == r["levels"]
        assert r["frontier-hwm"] >= 1
        assert r["transfer-bytes"] > 0
        seg_spans = [s for s in obs.tracer().spans()
                     if s["name"] == "checker.segment"]
        assert obs.tracer().recorded > before
        assert seg_spans and {s["phase"] for s in seg_spans} <= \
            {"compile", "execute"}
        assert seg_spans[-1]["level_end"] == r["levels"]

    def test_traced_and_untraced_verdicts_match(self, monkeypatch):
        # the kill-switch acceptance bar: JTPU_TRACE=0 changes nothing
        # about verdicts or level counts
        from jepsen_tpu.checker.tpu import check_history_tpu
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(400, n_procs=5, n_vals=8, seed=9,
                                      crash_p=0.01)
        monkeypatch.setenv("JTPU_TRACE", "1")
        r1 = check_history_tpu(h, CASRegister(), segment_iters=64)
        monkeypatch.setenv("JTPU_TRACE", "0")
        r0 = check_history_tpu(h, CASRegister(), segment_iters=64)
        assert r1["valid"] == r0["valid"]
        assert r1["levels"] == r0["levels"]
        assert r1["segment-levels"] == r0["segment-levels"]

    def test_run_artifacts_and_kill_switch(self, tmp_path, monkeypatch):
        from jepsen_tpu import core, generator as gen
        from jepsen_tpu.testing import atom_test

        from jepsen_tpu.checker import noop_checker

        def one_run(root):
            t = atom_test(**{"store-root": str(root),
                             "concurrency": 2, "nodes": ["a", "b"]})
            t["generator"] = gen.clients(gen.limit(
                10, lambda test, p: {"f": "read", "value": None}))
            t["checker"] = noop_checker()
            return core.run(t)

        monkeypatch.setenv("JTPU_TRACE", "1")
        t = one_run(tmp_path / "on")
        d = t["store-dir"]
        arts = sorted(os.listdir(d))
        assert "trace.jsonl" in arts and "metrics.json" in arts
        recs, stats = obs_trace.read_trace(
            os.path.join(d, "trace.jsonl"))
        names = {r["name"] for r in recs}
        assert {"core.run", "core.run_case", "client.invoke",
                "checker.check"} <= names
        snap = json.load(open(os.path.join(d, "metrics.json")))
        assert "jtpu_op_timeouts_total" in snap
        assert "jtpu_wal_fsync_seconds" in snap

        monkeypatch.setenv("JTPU_TRACE", "0")
        t = one_run(tmp_path / "off")
        arts = sorted(os.listdir(t["store-dir"]))
        assert "trace.jsonl" not in arts and "metrics.json" not in arts
        assert "progress.json" not in arts
        assert t["results"]["valid"] is True


# ---------------------------------------------------------------------------
# Device memory accounting (obs/devices.py)
# ---------------------------------------------------------------------------


class _FakeDev:
    def __init__(self, stats, platform="tpu", id=0):
        self._stats = stats
        self.platform = platform
        self.id = id

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


class TestDevices:
    def test_cpu_backend_is_a_silent_noop(self):
        # tier-1 runs JAX_PLATFORMS=cpu: memory_stats() is None there,
        # so the whole accounting stack must answer empty/None without
        # touching a gauge or raising
        rows = obs_devices.poll()
        assert rows == []
        assert obs_devices.headroom_ratio() is None

    def test_memory_stats_none_and_raising_tolerated(self):
        assert obs_devices.memory_stats(_FakeDev(None)) is None
        assert obs_devices.memory_stats(
            _FakeDev(RuntimeError("unsupported"))) is None
        assert obs_devices.memory_stats(_FakeDev({})) is None

    def test_poll_updates_gauges_and_headroom(self, monkeypatch):
        devs = [_FakeDev({"bytes_in_use": 600, "bytes_limit": 1000,
                          "peak_bytes_in_use": 800}, id=0),
                _FakeDev({"bytes_in_use": 100, "bytes_limit": 1000},
                         id=1)]
        monkeypatch.setattr(obs_devices, "_devices", lambda: devs)
        rows = obs_devices.poll()
        assert len(rows) == 2
        assert rows[0]["headroom"] == pytest.approx(0.4)
        assert obs_devices.headroom_ratio(rows) == pytest.approx(0.4)
        g = obs_metrics.REGISTRY.gauge("jtpu_device_bytes_in_use")
        assert g.value(device="tpu:0") == 600
        assert g.value(device="tpu:1") == 100
        assert obs_metrics.REGISTRY.gauge(
            "jtpu_device_peak_bytes_in_use").value(device="tpu:0") == 800

    def test_headroom_threshold_env(self, monkeypatch):
        monkeypatch.delenv("JTPU_HEADROOM_MIN", raising=False)
        assert obs_devices.headroom_threshold() == \
            obs_devices.DEFAULT_HEADROOM_MIN
        monkeypatch.setenv("JTPU_HEADROOM_MIN", "0.2")
        assert obs_devices.headroom_threshold() == 0.2
        monkeypatch.setenv("JTPU_HEADROOM_MIN", "junk")
        assert obs_devices.headroom_threshold() == \
            obs_devices.DEFAULT_HEADROOM_MIN

    def test_low_headroom_preemptively_halves_the_pool(self,
                                                       monkeypatch):
        # a fake backend reporting 1% headroom: the supervised search
        # halves its pool BEFORE any OOM, exactly once per rung
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.ops.encode import pack_with_init
        from jepsen_tpu.resilience import supervised_check_packed
        from jepsen_tpu.testing import simulate_register_history
        devs = [_FakeDev({"bytes_in_use": 990, "bytes_limit": 1000})]
        monkeypatch.setattr(obs_devices, "_devices", lambda: devs)
        monkeypatch.setenv("JTPU_HEADROOM_MIN", "0.05")
        # this test targets the REACTIVE halving path; the ahead-of-time
        # plan gate (doc/plan.md) would reject this synthetic 1 kB
        # device before the reactive machinery could ever be exercised
        monkeypatch.setenv("JTPU_PLAN_GATE", "0")
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=3)
        p, kernel = pack_with_init(h, CASRegister())
        r = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                    segment_iters=8)
        assert r["valid"] is True
        pre = [a for a in r["attempts"]
               if str(a.get("outcome", "")).startswith(
                   "preemptive-halve")]
        assert len(pre) == 1
        assert pre[0]["headroom"] == pytest.approx(0.01)
        assert r["rung"][0] == 32


# ---------------------------------------------------------------------------
# The search observatory (obs/observatory.py) + watch surfaces
# ---------------------------------------------------------------------------


class TestObservatory:
    def test_publish_ewma_eta_and_format(self):
        ob = obs_observatory.Observatory()
        assert ob.snapshot() is None
        ob.begin(level_budget=1000, rung=(64, 32, 8), segment_iters=100)
        ob.publish(level=100, frontier=40, segments=1, seg_seconds=0.1,
                   levels_delta=100, expansions=800)
        ob.publish(level=200, frontier=30, segments=2, seg_seconds=0.1,
                   levels_delta=100, expansions=800)
        p = ob.snapshot()
        assert p["state"] == "searching"
        assert p["level"] == 200 and p["frontier-rows"] == 30
        assert p["segments"] == 2 and p["segments-est"] == 10
        assert p["levels-per-s"] == pytest.approx(1000, rel=0.01)
        assert p["eta-s"] == pytest.approx(0.8, rel=0.01)
        line = obs_observatory.format_status(p)
        assert "level 200/1000" in line and "frontier 30 rows" in line
        ob.finish(valid=True, levels=250)
        p = ob.snapshot()
        assert p["state"] == "done" and p["valid"] is True
        assert p["level"] == 250
        # finishing again (early-out paths) must not clobber anything
        ob.finish(valid=False)
        assert ob.snapshot()["valid"] is True

    def test_progress_file_and_kill_switch(self, tmp_path,
                                           monkeypatch):
        monkeypatch.setenv("JTPU_TRACE", "1")
        ob = obs_observatory.Observatory()
        ob.attach(str(tmp_path))
        ob.begin(level_budget=10, rung=(8, 32, 2), segment_iters=4)
        ob.publish(level=4, frontier=2, segments=1, seg_seconds=0.01,
                   levels_delta=4, expansions=8)
        ob.finish(valid=True)
        doc = obs_observatory.read_progress(str(tmp_path))
        assert doc and doc["state"] == "done" and doc["level"] == 4
        # kill switch: attach refuses the sink, nothing is written
        monkeypatch.setenv("JTPU_TRACE", "0")
        off_dir = tmp_path / "off"
        off_dir.mkdir()
        ob2 = obs_observatory.Observatory()
        ob2.attach(str(off_dir))
        ob2.begin(level_budget=10, rung=(8, 32, 2), segment_iters=4)
        ob2.publish(level=4, frontier=2, segments=1, seg_seconds=0.01,
                    levels_delta=4, expansions=8)
        ob2.finish(valid=True)
        assert not os.path.exists(
            str(off_dir / obs_observatory.PROGRESS_NAME))
        # ...but the in-memory snapshot still works (run --watch path)
        assert ob2.snapshot()["state"] == "done"

    def test_read_progress_tolerates_garbage(self, tmp_path):
        assert obs_observatory.read_progress(str(tmp_path)) is None
        (tmp_path / obs_observatory.PROGRESS_NAME).write_text("{nope")
        assert obs_observatory.read_progress(str(tmp_path)) is None

    def test_supervised_search_publishes_live_progress(self, tmp_path):
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.ops.encode import pack_with_init
        from jepsen_tpu.resilience import supervised_check_packed
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=3)
        p, kernel = pack_with_init(h, CASRegister())
        obs_observatory.attach(str(tmp_path))
        try:
            r = supervised_check_packed(p, kernel, capacity=64,
                                        expand=8, segment_iters=8)
        finally:
            obs_observatory.detach()
        snap = obs_observatory.snapshot()
        assert snap["state"] == "done" and snap["valid"] is True
        assert snap["level"] == r["levels"]
        assert snap["segments"] == r["segments"]
        doc = obs_observatory.read_progress(str(tmp_path))
        assert doc and doc["state"] == "done"
        assert obs_metrics.REGISTRY.gauge(
            "jtpu_search_level").value() == r["levels"]
        assert obs_metrics.REGISTRY.gauge(
            "jtpu_search_inflight").value() == 0

    def test_live_status_printer(self):
        import io
        out = io.StringIO()
        obs_observatory.OBSERVATORY.begin(
            level_budget=100, rung=(8, 32, 2), segment_iters=10)
        obs_observatory.OBSERVATORY.publish(
            level=10, frontier=4, segments=1, seg_seconds=0.01,
            levels_delta=10, expansions=20)
        stop = obs_observatory.live_status_printer(interval=0.01,
                                                   out=out)
        import time as _t
        _t.sleep(0.1)
        stop()
        assert "# watch: level" in out.getvalue()


class TestWatchCLI:
    def test_watch_once_and_degradation(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = tmp_path / "run"
        d.mkdir()
        # no progress.json at all: a graceful line, exit 0 (the watch
        # path must be a silent no-op for pre-observatory runs)
        rc = cli.run(cli.default_commands(),
                     ["watch", "--store", str(d), "--once"])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "no search progress" in out
        (d / obs_observatory.PROGRESS_NAME).write_text(json.dumps(
            {"state": "searching", "ts": 1.0, "level": 50,
             "level-budget": 200, "frontier-rows": 8, "segments": 2,
             "segments-est": 20, "levels-per-s": 500.0,
             "configs-per-s": 4000.0, "eta-s": 0.3}))
        rc = cli.run(cli.default_commands(),
                     ["watch", "--store", str(d), "--once"])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "level 50/200" in out and "eta 0.3s" in out
        rc = cli.run(cli.default_commands(),
                     ["watch", "--store", str(tmp_path / "nope"),
                      "--once"])
        assert rc == cli.INVALID_ARGS


# ---------------------------------------------------------------------------
# XLA cost accounting
# ---------------------------------------------------------------------------


class TestCostAccounting:
    def _check(self, **kw):
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.ops.encode import pack_with_init
        from jepsen_tpu.resilience import supervised_check_packed
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=3)
        p, kernel = pack_with_init(h, CASRegister())
        return supervised_check_packed(p, kernel, capacity=64,
                                       expand=8, segment_iters=8, **kw)

    def test_supervised_result_carries_per_executable_cost(self):
        r = self._check()
        assert r["valid"] is True
        (ent,) = r["cost"]
        assert ent["kind"] == "segment"
        assert ent["flops"] > 0 and ent["bytes-accessed"] > 0
        assert ent["levels"] == r["levels"]
        assert ent["rung"] == [64, 32, 8]
        seg_spans = [s for s in obs.tracer().spans()
                     if s["name"] == "checker.segment"]
        assert seg_spans and seg_spans[-1]["flops"] == ent["flops"]

    def test_monolithic_and_keyed_carry_cost(self):
        from jepsen_tpu.checker.tpu import (check_history_tpu,
                                            check_keyed_tpu)
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=3)
        r = check_history_tpu(h, CASRegister(), segment_iters=0)
        assert r["cost"][0]["kind"] == "single"
        assert r["cost"][0]["flops"] > 0
        keyed = {k: simulate_register_history(60, n_procs=3, n_vals=4,
                                              seed=500 + k)
                 for k in range(3)}
        rk = check_keyed_tpu(keyed, CASRegister())
        assert rk["valid"] is True
        assert rk["cost"] and rk["cost"][0]["kind"] == "batch"
        assert rk["cost"][0]["keys"] == 3
        # the batch executable's cost lives at the TOP level only —
        # attaching it per key would overcount the work keys-fold
        assert all("cost" not in res
                   for res in rk["results"].values())

    def test_cost_absent_with_trace_off(self, monkeypatch):
        monkeypatch.setenv("JTPU_TRACE", "0")
        r = self._check()
        assert r["valid"] is True
        assert "cost" not in r

    def test_cost_analysis_failure_degrades_silently(self, monkeypatch):
        # a backend/jax without cost_analysis: verdicts unchanged, no
        # cost key, no exception — the tier-1 degradation contract
        from jepsen_tpu.checker import tpu as T

        def boom(fn, args):
            raise AttributeError("no cost_analysis on this backend")

        monkeypatch.setattr(T, "_cost_analysis", boom)
        monkeypatch.setattr(T, "_COST_BY_SHAPE", {})
        r = self._check()
        assert r["valid"] is True
        assert "cost" not in r

    def test_shard_balance_accounting(self):
        import numpy as np
        from jepsen_tpu.checker.tpu import _shard_balance
        pk = np.array([5, 4, 3, 2, 9, 0, 0, 0], np.int32)
        pa = np.array([1, 1, 1, 1, 1, 0, 0, 0], bool)
        bal = _shard_balance((pk, pk, pa), 2)
        assert bal["devices"] == 2
        assert bal["live-rows"] == [4, 1]
        assert bal["deepest-k"] == [5, 9]
        assert bal["imbalance-ratio"] == pytest.approx(1.6)
        assert obs_metrics.REGISTRY.gauge(
            "jtpu_shard_imbalance_ratio").value() == pytest.approx(1.6)
        # odd split: refuses rather than mis-attributing rows
        assert _shard_balance((pk, pk, pa), 3) is None


# ---------------------------------------------------------------------------
# Surfaces: web + CLI
# ---------------------------------------------------------------------------


class TestWebMetrics:
    def test_metrics_roundtrip_and_waterfall(self, tmp_path):
        import urllib.error
        from jepsen_tpu import web
        run = tmp_path / "t" / "20260804T000000.000"
        run.mkdir(parents=True)
        (run / "results.json").write_text('{"valid": true}')
        tr = obs_trace.Tracer(path=str(run / "trace.jsonl"))
        with tr.span("core.run"):
            with tr.span("checker.check"):
                pass
        tr.detach()
        obs_metrics.counter("jtpu_web_roundtrip_total",
                            "test series").inc(7, who="roundtrip")
        server = web.serve_background(root=str(tmp_path))
        base = f"http://127.0.0.1:{server.server_port}"
        try:
            with urllib.request.urlopen(base + "/metrics") as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                body = r.read().decode()
            assert "# TYPE jtpu_web_roundtrip_total counter" in body
            assert 'jtpu_web_roundtrip_total{who="roundtrip"} 7' in body
            home = urllib.request.urlopen(base + "/").read().decode()
            assert "/trace/t/20260804T000000.000" in home
            wf = urllib.request.urlopen(
                base + "/trace/t/20260804T000000.000").read().decode()
            assert "core.run" in wf and "span(s) over" in wf
            # a run without a trace 404s rather than erroring
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/trace/t/nope")
            assert ei.value.code == 404
        finally:
            server.shutdown()

    def test_live_endpoint(self, tmp_path):
        import urllib.error
        from jepsen_tpu import web
        run = tmp_path / "t" / "20260804T000001.000"
        run.mkdir(parents=True)
        server = web.serve_background(root=str(tmp_path))
        base = f"http://127.0.0.1:{server.server_port}"
        url = base + "/live/t/20260804T000001.000"
        try:
            # run exists but never published: progress null, not a 500
            with urllib.request.urlopen(url) as r:
                doc = json.load(r)
            assert r.status == 200 and doc["progress"] is None
            (run / obs_observatory.PROGRESS_NAME).write_text(
                json.dumps({"state": "searching", "ts": 7.5,
                            "level": 10, "level-budget": 100,
                            "frontier-rows": 4, "segments": 1}))
            with urllib.request.urlopen(url) as r:
                doc = json.load(r)
            assert doc["progress"]["level"] == 10
            # long-poll: already-seen ts blocks until the (capped)
            # wait elapses, fresh ts returns immediately
            import time as _t
            t0 = _t.monotonic()
            with urllib.request.urlopen(url + "?wait=1&since=7.5") as r:
                json.load(r)
            assert _t.monotonic() - t0 >= 0.9
            t0 = _t.monotonic()
            with urllib.request.urlopen(url + "?wait=5&since=7.0") as r:
                doc = json.load(r)
            assert _t.monotonic() - t0 < 2
            assert doc["progress"]["ts"] == 7.5
            # a missing run 404s with a JSON body
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/live/t/nope")
            assert ei.value.code == 404
            # the trace page of a progress-bearing run carries the strip
            (run / "trace.jsonl").write_text(
                '{"name": "core.run", "ts": 0, "dur": 5, "tid": 1, '
                '"sid": 1}\n')
            page = urllib.request.urlopen(
                base + "/trace/t/20260804T000001.000").read().decode()
            assert "liveBar" in page \
                and "/live/t/20260804T000001.000" in page
        finally:
            server.shutdown()


class TestTraceCLI:
    def _store_with_trace(self, tmp_path):
        d = tmp_path / "run"
        d.mkdir()
        tr = obs_trace.Tracer(path=str(d / "trace.jsonl"))
        with tr.span("core.run"):
            with tr.span("checker.segment", phase="execute"):
                pass
        tr.detach()
        return str(d)

    def test_export_chrome(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store_with_trace(tmp_path)
        out = str(tmp_path / "chrome.json")
        rc = cli.run(cli.default_commands(),
                     ["trace", "export", "--store", d, "-o", out])
        assert rc == cli.OK
        doc = json.load(open(out))
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"core.run", "checker.segment"} <= names

    def test_summary_and_missing_store(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store_with_trace(tmp_path)
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", d])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "# trace:" in out and "checker.segment" in out
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store",
                      str(tmp_path / "nope")])
        assert rc == cli.INVALID_ARGS

    def test_summary_top_self_time(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store_with_trace(tmp_path)
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", d, "--top", "5"])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "top" in out and "self" in out

    def test_self_time_rollup_subtracts_children(self):
        recs = [
            {"name": "outer", "ts": 0, "dur": 100, "tid": 1, "sid": 1},
            {"name": "inner", "ts": 10, "dur": 80, "tid": 1, "sid": 2,
             "pid": 1},
            {"name": "inner", "ts": 95, "dur": 4, "tid": 1, "sid": 3,
             "pid": 1},
        ]
        top = obs_trace.self_time_rollup(recs)
        # outer's 100ns minus its children's 84ns = 16ns of self time
        assert top["outer"] == {"count": 1, "self-ns": 16,
                                "p95-ns": 16}
        assert top["inner"]["count"] == 2
        assert top["inner"]["self-ns"] == 84
        assert top["inner"]["p95-ns"] == 80

    def test_recover_emits_trace_summary(self, tmp_path, capsys):
        # a dead run with a WAL and a trace: recover prints the
        # `# trace:` span-count line next to `# recovery:`/`# lint:`
        from jepsen_tpu import cli, journal, store
        from jepsen_tpu.history import Op
        d = tmp_path / "kv" / "r1"
        d.mkdir(parents=True)
        j = journal.Journal(str(d / "history.wal"))
        j.append(Op(type="invoke", f="read", process=0, time=1))
        j.append(Op(type="ok", f="read", value=1, process=0, time=2))
        j.close()
        tr = obs_trace.Tracer(path=str(d / "trace.jsonl"))
        with tr.span("client.invoke", f="read"):
            pass
        tr.detach()
        store.write_state(str(d), "running")
        st = json.load(open(d / "run.state"))
        st["pid"] = 2 ** 22 + 1  # beyond pid_max: reads as dead
        (d / "run.state").write_text(json.dumps(st))
        assert store.run_status(str(d)) == "dead"
        rc = cli.run(cli.default_commands(),
                     ["recover", "--store", str(d), "--no-analyze"])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "# recovery:" in out and "# lint:" in out
        assert "# trace: 1 span(s) recovered from trace.jsonl" in out


# ---------------------------------------------------------------------------
# The lint rule guarding the discipline
# ---------------------------------------------------------------------------


class TestTraceInJitLint:
    def _lint(self, tmp_path, body):
        from jepsen_tpu.analysis import jax_lint
        p = tmp_path / "mod.py"
        p.write_text(body)
        return jax_lint.lint_file(str(p), root=str(tmp_path))

    def test_flags_clock_and_span_in_traced_body(self, tmp_path):
        findings = self._lint(tmp_path, (
            "import time\n"
            "from jepsen_tpu import obs\n"
            "from jax import lax\n"
            "def search(x):\n"
            "    def body(c):\n"
            "        t0 = time.monotonic()\n"
            "        with obs.span('level'):\n"
            "            c = c + 1\n"
            "        return c\n"
            "    return lax.while_loop(lambda c: c < x, body, 0)\n"))
        rules = [f.rule for f in findings]
        assert rules.count("JAX-TRACE-IN-JIT") == 2
        assert all(f.severity == "error" for f in findings
                   if f.rule == "JAX-TRACE-IN-JIT")

    def test_host_side_timing_is_clean(self, tmp_path):
        # the sanctioned pattern: clock + span around the device call,
        # outside any traced body
        findings = self._lint(tmp_path, (
            "import time\n"
            "import jax\n"
            "from jepsen_tpu import obs\n"
            "def timed(fn, *args):\n"
            "    with obs.span('checker.device'):\n"
            "        t0 = time.perf_counter()\n"
            "        out = jax.block_until_ready(fn(*args))\n"
            "        dt = time.perf_counter() - t0\n"
            "    return out, dt\n"))
        assert not [f for f in findings
                    if f.rule == "JAX-TRACE-IN-JIT"]

    def test_flags_progress_publish_in_traced_body(self, tmp_path):
        findings = self._lint(tmp_path, (
            "from jepsen_tpu.obs import observatory\n"
            "from jax import lax\n"
            "def search(x):\n"
            "    def body(c):\n"
            "        observatory.publish(level=c)\n"
            "        return c + 1\n"
            "    return lax.while_loop(lambda c: c < x, body, 0)\n"))
        rules = [f.rule for f in findings]
        assert rules.count("JAX-TRACE-IN-JIT") == 1

    def test_allowlist_suppresses_sanctioned_site(self, tmp_path,
                                                  monkeypatch):
        from jepsen_tpu.analysis import jax_lint
        body = (
            "from jepsen_tpu.obs import observatory\n"
            "from jax import lax\n"
            "def supervise(x):\n"
            "    def body(c):\n"
            "        observatory.publish(level=c)\n"
            "        return c + 1\n"
            "    return lax.while_loop(lambda c: c < x, body, 0)\n")
        p = tmp_path / "mod.py"
        p.write_text(body)
        findings = jax_lint.lint_file(str(p), root=str(tmp_path))
        assert [f for f in findings if f.rule == "JAX-TRACE-IN-JIT"]
        monkeypatch.setattr(jax_lint, "TRACE_IN_JIT_ALLOWLIST",
                            (("mod.py", "supervise"),))
        findings = jax_lint.lint_file(str(p), root=str(tmp_path))
        assert not [f for f in findings
                    if f.rule == "JAX-TRACE-IN-JIT"]

    def test_repo_checker_stack_obeys_the_rule(self):
        # the instrumented production files themselves must be clean
        from jepsen_tpu.analysis import jax_lint
        for rel in ("jepsen_tpu/checker/tpu.py",
                    "jepsen_tpu/resilience.py",
                    "jepsen_tpu/obs/trace.py",
                    "jepsen_tpu/obs/observatory.py",
                    "jepsen_tpu/obs/devices.py"):
            findings = jax_lint.lint_file(os.path.join(REPO, rel),
                                          root=REPO)
            assert not [f for f in findings
                        if f.rule == "JAX-TRACE-IN-JIT"], rel


# ---------------------------------------------------------------------------
# Request-scoped distributed tracing (doc/observability.md, "Request
# tracing"): W3C traceparent plumbing, the thread-local trace-context
# slot, and the cross-process stitcher
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_traceparent_roundtrip(self):
        tid = obs_trace.new_trace_id()
        assert len(tid) == 32 and int(tid, 16) >= 0
        hdr = obs_trace.format_traceparent(tid, "00f067aa0ba902b7")
        assert hdr == f"00-{tid}-00f067aa0ba902b7-01"
        assert obs_trace.parse_traceparent(hdr) == \
            (tid, "00f067aa0ba902b7")

    def test_format_traceparent_renders_integer_sids(self):
        hdr = obs_trace.format_traceparent("ab" * 16, 7)
        assert hdr == f"00-{'ab' * 16}-{7:016x}-01"
        # with no span id yet (echoing at admission) a random non-zero
        # one is minted — the spec forbids all-zero span ids
        minted = obs_trace.format_traceparent("ab" * 16)
        _, sid = obs_trace.parse_traceparent(minted)
        assert int(sid, 16) != 0

    def test_parse_traceparent_rejects_malformed(self):
        bad = (None, 7, "", "garbage", "00-short-beef-01",
               "00-" + "g" * 32 + "-" + "1" * 16 + "-01",   # non-hex
               "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # zero tid
               "00-" + "1" * 32 + "-" + "0" * 16 + "-01")   # zero sid
        for hdr in bad:
            assert obs_trace.parse_traceparent(hdr) is None, hdr

    def test_context_stamps_records_and_guard_restores(self):
        tr = obs_trace.Tracer()
        tid = obs_trace.new_trace_id()
        with tr.span("untraced"):
            pass
        with tr.context(tid, "00f067aa0ba902b7"):
            assert tr.current_context() == (tid, "00f067aa0ba902b7")
            with tr.span("outer"):
                with tr.span("inner"):
                    pass
            # nested guard (a gang member re-run) restores the OUTER
            # request's id, not None
            other = obs_trace.new_trace_id()
            with tr.context(other):
                tr.event("rerun")
            assert tr.current_context()[0] == tid
        assert tr.current_context() == (None, None)
        recs = {r["name"]: r for r in tr.spans()}
        assert "trace" not in recs["untraced"]
        assert recs["outer"]["trace"] == tid
        assert recs["inner"]["trace"] == tid
        assert recs["rerun"]["trace"] == other
        # only the context ROOT carries the inbound parent span id
        assert recs["outer"]["parent"] == "00f067aa0ba902b7"
        assert "parent" not in recs["inner"]

    def test_context_is_thread_local(self):
        tr = obs_trace.Tracer()
        tid = obs_trace.new_trace_id()
        seen = {}

        def worker():
            seen["ctx"] = tr.current_context()
            with tr.span("other-thread"):
                pass

        with tr.context(tid):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["ctx"] == (None, None)
        recs = {r["name"]: r for r in tr.spans()}
        assert "trace" not in recs["other-thread"]

    def test_by_trace_groups_and_read_trace_counts(self, tmp_path):
        p = str(tmp_path / "trace.jsonl")
        tr = obs_trace.Tracer(path=p)
        t1, t2 = obs_trace.new_trace_id(), obs_trace.new_trace_id()
        with tr.context(t1):
            with tr.span("a"):
                pass
        with tr.context(t2):
            with tr.span("b"):
                pass
        with tr.span("background"):
            pass
        tr.detach()
        recs, stats = obs_trace.read_trace(p)
        assert stats["traces"] == 2
        groups = obs_trace.by_trace(recs)
        assert set(groups) == {t1, t2}
        assert [r["name"] for r in groups[t1]] == ["a"]

    def test_sync_event_carries_wall_anchor(self, tmp_path, monkeypatch):
        monkeypatch.delenv("JTPU_TRACE", raising=False)
        p = str(tmp_path / "trace.jsonl")
        obs_trace.tracer().attach(p)
        try:
            obs_trace.sync_event()
        finally:
            obs_trace.tracer().detach()
        recs, _ = obs_trace.read_trace(p)
        sync = [r for r in recs if r["name"] == "trace.sync"]
        assert sync and isinstance(sync[0]["wall_ns"], int)
        assert sync[0]["wall_ns"] > 10 ** 18   # nanoseconds since 1970


class TestStitchRequest:
    def _host(self, d, tid, names, epoch_wall, ts0=1000, step=500):
        """Write one fake host dir: a trace.sync anchor claiming this
        tracer's monotonic epoch began at ``epoch_wall`` ns wall time,
        then spans under ``tid``."""
        os.makedirs(d, exist_ok=True)
        recs = [{"name": "trace.sync", "ts": 0, "dur": 0, "tid": 1,
                 "sid": 1, "wall_ns": epoch_wall}]
        ts = ts0
        for i, name in enumerate(names):
            recs.append({"name": name, "ts": ts, "dur": 100, "tid": 1,
                         "sid": i + 2, "trace": tid})
            ts += step
        with open(os.path.join(d, "trace.jsonl"), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def test_two_processes_align_on_wall_clock(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        tid = obs_trace.new_trace_id()
        base = 1_700_000_000_000_000_000
        self._host(str(tmp_path), tid, ["serve.request"], base)
        # the worker booted 5000ns later: identical raw ts values must
        # land AFTER the daemon's on the aligned timeline
        self._host(str(tmp_path / "w0"), tid, ["checker.segment"],
                   base + 5000)
        out = obs_fleet.stitch_request(str(tmp_path), tid)
        assert out["trace-id"] == tid and out["method"] == "wall-clock"
        assert len(out["hosts"]) == 2
        names = [r["name"] for r in out["records"]]
        assert names == ["serve.request", "checker.segment"]
        seg = out["records"][1]
        assert seg["ts"] == 1000 + 5000 and seg["host"] == "w0"

    def test_filters_to_one_trace_and_tolerates_extra_dirs(
            self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        tid, noise = obs_trace.new_trace_id(), obs_trace.new_trace_id()
        base = 1_700_000_000_000_000_000
        self._host(str(tmp_path / "main"), tid,
                   ["serve.request"], base)
        self._host(str(tmp_path / "elsewhere"), noise,
                   ["other.request"], base)
        out = obs_fleet.stitch_request(
            str(tmp_path / "main"), tid,
            extra_dirs=[str(tmp_path / "elsewhere"),
                        str(tmp_path / "vanished")])
        assert [r["name"] for r in out["records"]] == ["serve.request"]

    def test_single_process_needs_no_alignment(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        tid = obs_trace.new_trace_id()
        self._host(str(tmp_path), tid, ["a", "b"],
                   1_700_000_000_000_000_000)
        out = obs_fleet.stitch_request(str(tmp_path), tid)
        assert out["method"] is None
        assert [r["name"] for r in out["records"]] == ["a", "b"]

    def test_to_chrome_renders_one_process_per_host(self, tmp_path):
        from jepsen_tpu.obs import fleet as obs_fleet
        tid = obs_trace.new_trace_id()
        base = 1_700_000_000_000_000_000
        self._host(str(tmp_path), tid, ["serve.request"], base)
        self._host(str(tmp_path / "w0"), tid, ["checker.segment"],
                   base)
        out = obs_fleet.stitch_request(str(tmp_path), tid)
        doc = obs_fleet.to_chrome({"hosts": out["hosts"],
                                   "trace": out["records"]})
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert len(metas) == 2
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["pid"] for e in spans} == {1, 2}

    def test_request_trace_html_renders_waterfall(self, tmp_path):
        from jepsen_tpu import web
        from jepsen_tpu.obs import fleet as obs_fleet
        tid = obs_trace.new_trace_id()
        base = 1_700_000_000_000_000_000
        self._host(str(tmp_path), tid, ["serve.request"], base)
        self._host(str(tmp_path / "w0"), tid, ["checker.segment"],
                   base + 1000)
        out = obs_fleet.stitch_request(str(tmp_path), tid)
        html_text = web.request_trace_html(out)
        assert tid in html_text
        assert "serve.request" in html_text
        assert "checker.segment" in html_text
        assert "w0" in html_text


class TestTraceSummaryIntegrity:
    def test_summary_surfaces_torn_corrupt_and_json(self, tmp_path,
                                                    capsys):
        from jepsen_tpu import cli
        d = tmp_path / "run"
        d.mkdir()
        tr = obs_trace.Tracer(path=str(d / "trace.jsonl"))
        with tr.context(obs_trace.new_trace_id()):
            with tr.span("checker.segment", phase="execute"):
                pass
        tr.detach()
        with open(d / "trace.jsonl", "ab") as f:
            f.write(b'{"name": "mid", "ts": 1}garbage\n')  # corrupt
            f.write(b'{"name": "torn", "ts": 12')          # torn tail
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", str(d)])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "# trace: integrity: 1 torn, 1 corrupt line(s); " \
               "1 request trace id(s)" in out
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", str(d),
                      "--format", "json"])
        assert rc == cli.OK
        doc = json.loads(capsys.readouterr().out)
        assert doc["stats"]["torn"] == 1
        assert doc["stats"]["corrupt"] == 1
        assert doc["stats"]["traces"] == 1


class TestTraceRequestCLI:
    def _serve_store(self, tmp_path):
        """A dead serve store: serve.wal maps a request id to its
        trace id, trace.jsonl holds the spans."""
        from jepsen_tpu import serve as serve_ns
        d = tmp_path / "serve"
        d.mkdir()
        tid = obs_trace.new_trace_id()
        j = serve_ns.RequestJournal(str(d / "serve.wal"))
        j.append({"event": "accepted", "id": "r-000001", "trace": tid})
        j.append({"event": "done", "id": "r-000001"})
        j.close()
        tr = obs_trace.Tracer(path=str(d / "trace.jsonl"))
        with tr.context(tid):
            with tr.span("serve.request", id="r-000001"):
                with tr.span("checker.segment", phase="execute"):
                    pass
            tr.event("serve.verdict", id="r-000001")
        tr.detach()
        return str(d), tid

    def test_request_id_resolves_through_wal(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d, tid = self._serve_store(tmp_path)
        rc = cli.run(cli.default_commands(),
                     ["trace", "request", "r-000001", "--store", d])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert tid in out
        for name in ("serve.request", "checker.segment",
                     "serve.verdict"):
            assert name in out

    def test_literal_trace_id_and_json_format(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d, tid = self._serve_store(tmp_path)
        rc = cli.run(cli.default_commands(),
                     ["trace", "request", tid, "--store", d,
                      "--format", "json"])
        assert rc == cli.OK
        doc = json.loads(capsys.readouterr().out)
        assert doc["trace-id"] == tid
        assert [r["name"] for r in doc["records"]] == \
            ["serve.request", "checker.segment", "serve.verdict"]

    def test_unresolvable_id_fails_cleanly(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d, _ = self._serve_store(tmp_path)
        rc = cli.run(cli.default_commands(),
                     ["trace", "request", "r-nope", "--store", d])
        assert rc == cli.INVALID_ARGS
        assert "couldn't resolve" in capsys.readouterr().err
