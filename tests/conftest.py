"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths compile and run without TPU hardware."""

import os
import sys

# Unconditional: the ambient environment may pin JAX_PLATFORMS to a real
# TPU (e.g. axon); tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import jax
    # The ambient TPU plugin (axon) can override JAX_PLATFORMS; the config
    # update is authoritative.
    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-scale (10k-op) checker runs; deselect with "
        "-m 'not slow'")
    config.addinivalue_line(
        "markers", "chaos: injected-fault resilience scenarios (OOM, "
        "wedge, kill-mid-segment, hung client); tools/chaos_matrix.py "
        "sweeps the grid standalone with -m chaos")
    config.addinivalue_line(
        "markers", "lint: static-analysis subsystem tests "
        "(tests/test_lint.py): per-pass fixtures, the pre-search "
        "history gate, and the repo self-lint against lint.baseline")
    config.addinivalue_line(
        "markers", "obs: observability subsystem tests "
        "(tests/test_obs.py): span tracer, metrics registry, "
        "Prometheus/Chrome exports, run artifacts, and the "
        "JTPU_TRACE kill switch")
    config.addinivalue_line(
        "markers", "plan: search-plan verifier tests "
        "(tests/test_plan.py): bucket enumeration, zero-compile "
        "abstract evaluation, footprint math, the pre-search plan "
        "gate, and the JTPU_PLAN_GATE kill switch")
    config.addinivalue_line(
        "markers", "prof: device-profiling + fleet telemetry tests "
        "(tests/test_prof.py): jax.profiler capture scoping, the "
        "JTPU_PROF kill switch, device-trace parse/merge, kernel "
        "rollups, compile-cache accounting, and the fleet merge")
    config.addinivalue_line(
        "markers", "fleet: elastic fleet layer tests "
        "(tests/test_fleet.py): pool split/merge at the merge-sort "
        "barrier, host-loss re-meshing, work-stealing rebalance, "
        "join admission, the DCN failure class, changed-mesh "
        "checkpoint resume, and the JTPU_FLEET kill switch")
    config.addinivalue_line(
        "markers", "serve: check-daemon + engine tests "
        "(tests/test_serve.py): the explicit executable Engine and its "
        "warm-cache accounting, the request WAL + restart replay, "
        "admission control / backpressure / fair dequeue, per-bucket "
        "circuit breakers, per-request deadlines, drain, and the "
        "JTPU_SERVE kill-switch identity")
    config.addinivalue_line(
        "markers", "explain: search-analytics + verdict-explain tests "
        "(tests/test_searchstats.py): the per-level counter lane and "
        "its JTPU_TRACE=0 byte-identity, searchstats rollups, the "
        "contention/decomposability profiler, and the jtpu explain "
        "report for valid/invalid/unknown fixtures")


def pytest_collection_modifyitems(config, items):
    if config.getoption("-m"):
        return
    import pytest as _pytest
    skip = _pytest.mark.skip(reason="slow: run with -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
