"""Exhaustive model-check of the aerospike clustering spec.

TLC isn't in this image, so this mirrors the transition system of
jepsen_tpu/suites/resources/aerospike_clustering.tla in Python and
BFS-explores the ENTIRE reachable state space for small rosters,
checking the spec's invariants in every state. The spec file is also
parsed for structural drift (constants/actions/invariants present)."""

import itertools
import os

SPEC = os.path.join(os.path.dirname(__file__), "..", "jepsen_tpu",
                    "suites", "resources", "aerospike_clustering.tla")


def all_pairs(roster):
    return frozenset(frozenset(p) for p in itertools.combinations(roster, 2))


def reachable(links, a, b):
    return a == b or frozenset((a, b)) in links


def component(links, roster, n):
    return frozenset(m for m in roster if reachable(links, n, m))


def majority(s, roster):
    return 2 * len(s) > len(roster)


def explore(roster):
    """BFS the full reachable state space: states are (links, views)."""
    init = (all_pairs(roster),
            tuple(frozenset(roster) for _ in roster))
    nodes = sorted(roster)
    idx = {n: i for i, n in enumerate(nodes)}
    seen = {init}
    frontier = [init]
    while frontier:
        links, views = frontier.pop()
        yield links, views, nodes, idx
        succs = []
        # Cut / Heal every pair
        for p in all_pairs(roster):
            if p in links:
                succs.append((links - {p}, views))
            else:
                succs.append((links | {p}, views))
        # Observe every node
        for n in nodes:
            v2 = list(views)
            v2[idx[n]] = component(links, roster, n)
            succs.append((links, tuple(v2)))
        for s in succs:
            if s not in seen:
                seen.add(s)
                frontier.append(s)


def check_invariants(roster):
    checked = 0
    for links, views, nodes, idx in explore(roster):
        checked += 1
        for n in nodes:
            v = views[idx[n]]
            # TypeOK
            assert n in v and v <= frozenset(roster)
            current = v == component(links, roster, n)
            # CurrentViewsAreReachable
            if current:
                assert all(reachable(links, n, m) for m in v), \
                    (links, views, n)
        # NoDisjointDualMajorities
        for a in nodes:
            for b in nodes:
                if a == b:
                    continue
                va, vb = views[idx[a]], views[idx[b]]
                if (va == component(links, roster, a)
                        and vb == component(links, roster, b)
                        and not (va & vb)):
                    assert not (majority(va, roster)
                                and majority(vb, roster)), \
                        (links, views, a, b)
    return checked


def find_bridge_dual_majority(roster):
    """The model-checked NEGATIVE result: a reachable state where two
    CURRENT, OVERLAPPING views both claim a roster majority."""
    for links, views, nodes, idx in explore(roster):
        for a in nodes:
            for b in nodes:
                if a == b or reachable(links, a, b):
                    continue
                va, vb = views[idx[a]], views[idx[b]]
                if (va == component(links, roster, a)
                        and vb == component(links, roster, b)
                        and majority(va, roster)
                        and majority(vb, roster)):
                    return links, va, vb
    return None


class TestClusteringModel:
    def test_three_node_roster_exhaustive(self):
        n = check_invariants(["a", "b", "c"])
        # 2^3 link states x (views reachable) — must be a real space
        assert n > 100

    def test_four_node_roster_exhaustive(self):
        n = check_invariants(["a", "b", "c", "d"])
        assert n > 5000

    def test_bridge_partition_admits_dual_majorities(self):
        # The spec's documented hazard: under the jepsen bridge topology
        # two mutually-unreachable nodes hold CURRENT majority views
        # overlapping at the bridge node — heartbeat reachability alone
        # cannot prevent split-brain (hence succession agreement, hence
        # the suite's bridge nemesis).
        hit = find_bridge_dual_majority(["a", "b", "c"])
        assert hit is not None
        links, va, vb = hit
        assert va & vb                      # overlap: the bridge node

    def test_stale_views_can_claim_dual_majorities(self):
        # The bug window the spec deliberately permits (and the nemesis
        # schedule hammers): immediately after a cut, BOTH sides' stale
        # views still claim a full-roster majority. The invariant only
        # binds CURRENT views — this documents why the lag matters.
        roster = ["a", "b", "c"]
        links = all_pairs(roster) - {frozenset(("a", "b"))}
        stale = frozenset(roster)
        assert majority(stale, roster)
        assert not reachable(links, "a", "b")
        # both a and b hold the stale full view: dual majority, allowed
        # only because neither is current
        assert stale != component(links, roster, "a")

    def test_spec_file_structure(self):
        src = open(SPEC).read()
        for needle in ("MODULE aerospike_clustering", "CONSTANT Roster",
                       "Cut(a, b)", "Heal(a, b)", "Observe(n)",
                       "NoDisjointDualMajorities",
                       "CurrentViewsAreReachable", "EventuallyCurrent"):
            assert needle in src, needle
        cfg = open(SPEC.replace(".tla", ".cfg")).read()
        assert "INVARIANT Invariants" in cfg
        assert "Roster = {n1, n2, n3, n4, n5}" in cfg
