"""Search analytics (doc/observability.md "Search analytics"): the
per-level counter lane the device search carries, its JTPU_TRACE=0
byte-identity, the searchstats.json rollups, the host-side contention
/ decomposability profiler, and the `jtpu explain` verdict reports."""

import json
import os

import numpy as np
import pytest

from jepsen_tpu import testing
from jepsen_tpu.checker import tpu as T
from jepsen_tpu.history import History, Op
from jepsen_tpu.models import CASRegister
from jepsen_tpu.obs import searchstats as obs_searchstats

pytestmark = pytest.mark.explain

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def H(*rows):
    return History.of([
        Op(type=t, f=f, value=v, process=p, time=i)
        for i, (p, t, f, v) in enumerate(rows)
    ])


def _hist(n=40, seed=2, procs=3, overlap=0.6):
    return testing.simulate_register_history(n, n_procs=procs,
                                             seed=seed,
                                             overlap_p=overlap)


# ---------------------------------------------------------------------------
# The counter lane (checker/tpu.py carry index 13)
# ---------------------------------------------------------------------------


class TestCounterLane:
    def test_cols_match_kernel(self):
        # obs/searchstats.py duplicates the column catalog so the obs
        # package stays jax-free; the two MUST agree or every rollup
        # silently misattributes
        assert obs_searchstats.COLS == T.SEARCHSTAT_COLS
        assert obs_searchstats.NSTAT == T.NSTAT == 5

    def test_counters_populate(self, monkeypatch, tmp_path):
        monkeypatch.setenv("JTPU_TRACE", "1")
        obs_searchstats.attach(str(tmp_path))
        try:
            out = T.check_history_tpu(_hist(), CASRegister())
        finally:
            obs_searchstats.detach()
        assert out["valid"] is True
        ss = out["searchstats"]
        assert ss["levels"] == out["levels"]
        assert ss["expanded-total"] > 0
        assert ss["frontier-peak"] >= 1
        assert 0.0 <= ss["dup-rate"] <= 1.0
        doc = json.loads((tmp_path / "searchstats.json").read_text())
        assert doc["cols"] == list(T.SEARCHSTAT_COLS)
        assert len(doc["levels"]) == out["levels"]
        # every row is the NSTAT-wide int vector the kernel wrote
        assert all(len(r) == T.NSTAT for r in doc["levels"])

    def test_segmented_matches_monolithic_bitwise(self, monkeypatch,
                                                  tmp_path):
        # the acceptance bar: the segmented (checkpointed, supervised)
        # search and the monolithic one must write the SAME counters —
        # the lane rides the carry across segment barriers untouched
        monkeypatch.setenv("JTPU_TRACE", "1")
        h = _hist()
        d1, d2 = tmp_path / "mono", tmp_path / "seg"
        d1.mkdir(), d2.mkdir()
        obs_searchstats.attach(str(d1))
        try:
            out_m = T.check_history_tpu(h, CASRegister())
        finally:
            obs_searchstats.detach()
        obs_searchstats.attach(str(d2))
        try:
            out_s = T.check_history_tpu(h, CASRegister(),
                                        segment_iters=4)
        finally:
            obs_searchstats.detach()
        assert out_m["valid"] is True and out_s["valid"] is True
        l1 = json.loads((d1 / "searchstats.json").read_text())["levels"]
        l2 = json.loads((d2 / "searchstats.json").read_text())["levels"]
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        assert out_m["searchstats"] == out_s["searchstats"]

    def test_trace_off_identity(self, monkeypatch, tmp_path):
        # JTPU_TRACE=0 compiles the original 13-tuple carry: no stats
        # in the result, no searchstats.json artifact, and the verdict
        # fields bit-identical to a counters-on run
        h = _hist()
        monkeypatch.setenv("JTPU_TRACE", "1")
        out_on = T.check_history_tpu(h, CASRegister(), segment_iters=4)
        monkeypatch.setenv("JTPU_TRACE", "0")
        obs_searchstats.attach(str(tmp_path))
        try:
            out_off = T.check_history_tpu(h, CASRegister(),
                                          segment_iters=4)
        finally:
            obs_searchstats.detach()
        assert "searchstats" not in out_off
        assert not (tmp_path / "searchstats.json").exists()
        # deterministic verdict fields are unchanged by the lane
        for k in ("valid", "levels", "rung"):
            assert out_off.get(k) == out_on.get(k), k

    def test_trace_off_carry_shape(self):
        # the host-side carry constructor mirrors the traced one: no
        # stats rows -> the original 13-tuple, rows -> a 14th lane of
        # [rows, NSTAT] int32 zeros
        cols = {"ini": 0}
        c13 = T._carry0_host(8, 16, 4, 0, 0)
        assert len(c13) == 13
        c14 = T._carry0_host(8, 16, 4, 0, 0, stats_rows=6)
        assert len(c14) == 14
        assert c14[13].shape == (6, T.NSTAT)
        assert c14[13].dtype == np.int32
        assert not c14[13].any()
        del cols

    def test_fit_carry_stats_normalizes_checkpoints(self):
        # a checkpoint taken under the other JTPU_TRACE setting must
        # resume against the executable the CURRENT setting compiled
        from jepsen_tpu import resilience
        c13 = T._carry0_host(8, 16, 4, 0, 0)
        grown = resilience._fit_carry_stats(c13, True, 5)
        assert len(grown) == 14 and grown[13].shape == (6, T.NSTAT)
        shrunk = resilience._fit_carry_stats(grown, False, 5)
        assert len(shrunk) == 13
        # already-fitting carries pass through untouched
        assert resilience._fit_carry_stats(c13, False, 5) is c13

    def test_checkpoint_roundtrips_stats_lane(self, tmp_path):
        from jepsen_tpu import resilience
        carry = T._carry0_host(8, 16, 4, 0, 0, stats_rows=3)
        carry = carry[:13] + (np.arange(3 * T.NSTAT, dtype=np.int32)
                              .reshape(3, T.NSTAT),)
        p = str(tmp_path / "ck.npz")

        def ck(c):
            return resilience.Checkpoint(carry=c, rung=(8, 16, 4),
                                         window=16, expand_eff=4,
                                         crash_width=0, segment=1)

        ck(carry).save(p)
        back = resilience.Checkpoint.load(p)
        assert len(back.carry) == 14
        np.testing.assert_array_equal(back.carry[13], carry[13])
        # a pre-lane 13-tuple checkpoint still loads (no slog in npz)
        ck(carry[:13]).save(p)
        assert len(resilience.Checkpoint.load(p).carry) == 13

    def test_keyed_batch_path_carries_no_lane(self, monkeypatch):
        # the dense keyed-batch bench scenario is the overhead
        # criterion's subject: the keyed/gang/sharded paths keep the
        # lane OFF even with tracing on, so counters cost those
        # executables exactly nothing (identity, not a timing bound)
        monkeypatch.setenv("JTPU_TRACE", "1")
        keyed = {k: _hist(16, seed=k, procs=2) for k in range(3)}
        out = T.check_keyed_tpu(keyed, CASRegister())
        assert out["valid"] is True
        assert "searchstats" not in out
        assert not any("searchstats" in (r or {})
                       for r in (out.get("results") or {}).values()
                       if isinstance(r, dict))


# ---------------------------------------------------------------------------
# Rollups + the searchstats.json artifact (obs/searchstats.py)
# ---------------------------------------------------------------------------


class TestRollup:
    LEVELS = np.array([
        # expanded, dup, dominated, trunc, frontier
        [4, 1, 0, 0, 3],
        [6, 2, 1, 1, 4],
        [2, 0, 1, 0, 1],
    ], np.int32)

    def test_rollup_math(self):
        ss = obs_searchstats.rollup(self.LEVELS)
        assert ss["levels"] == 3
        assert ss["expanded-total"] == 12
        assert ss["dup-kills"] == 3
        assert ss["dominance-kills"] == 2
        assert ss["trunc-losses"] == 1
        assert ss["frontier-area"] == 8
        assert ss["frontier-peak"] == 4
        # dup-rate = dup / (dup + dominated + trunc + frontier)
        assert ss["dup-rate"] == pytest.approx(3 / 14, abs=1e-4)
        assert ss["prune-efficiency"] == pytest.approx(5 / 14,
                                                       abs=1e-4)

    def test_rollup_empty(self):
        ss = obs_searchstats.rollup(np.zeros((0, 5), np.int32))
        assert ss["levels"] == 0 and ss["dup-rate"] == 0.0

    def test_record_replaces_prefix(self, tmp_path, monkeypatch):
        # record() carries REPLACE semantics: each barrier rewrites the
        # full per-level prefix, so a torn write self-heals next time
        monkeypatch.setenv("JTPU_TRACE", "1")
        obs_searchstats.attach(str(tmp_path))
        try:
            obs_searchstats.record(self.LEVELS[:2], rung=(8, 16, 4))
            obs_searchstats.finalize(
                obs_searchstats.rollup(self.LEVELS[:2]))
            obs_searchstats.record(self.LEVELS, rung=(8, 16, 4))
            obs_searchstats.finalize(
                obs_searchstats.rollup(self.LEVELS))
        finally:
            obs_searchstats.detach()
        doc = obs_searchstats.read_searchstats(str(tmp_path))
        assert len(doc["levels"]) == 3
        assert doc["summary"]["trunc-losses"] == 1

    def test_trace_off_writes_nothing(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JTPU_TRACE", "0")
        obs_searchstats.attach(str(tmp_path))
        try:
            obs_searchstats.record(self.LEVELS, rung=(8, 16, 4))
            obs_searchstats.finalize(obs_searchstats.rollup(self.LEVELS))
        finally:
            obs_searchstats.detach()
        assert not (tmp_path / "searchstats.json").exists()

    def test_read_is_torn_tolerant(self, tmp_path):
        assert obs_searchstats.read_searchstats(str(tmp_path)) is None
        p = tmp_path / "searchstats.json"
        p.write_text('{"ts": 1, "levels": [[3, 1')  # torn mid-write
        assert obs_searchstats.read_searchstats(str(tmp_path)) is None
        p.write_text(json.dumps(
            {"ts": 1, "cols": list(obs_searchstats.COLS),
             "levels": [[1, 2, 3, 4, 5], "garbage", [1, 2]],
             "summary": {}}))
        doc = obs_searchstats.read_searchstats(str(tmp_path))
        # malformed rows are filtered, not fatal
        assert doc["levels"] == [[1, 2, 3, 4, 5]]

    def test_sparkline(self):
        line = obs_searchstats.sparkline([0, 1, 2, 4, 8])
        assert len(line) == 5
        assert line[0] == "▁" and line[-1] == "█"
        # long series are max-downsampled to the width
        assert len(obs_searchstats.sparkline(list(range(500)),
                                             width=48)) == 48
        assert obs_searchstats.sparkline([]) == ""


# ---------------------------------------------------------------------------
# Live progress + CLI surfaces (satellite: dup-rate/trunc bits)
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_observatory_carries_analytics_bits(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("JTPU_TRACE", "1")
        from jepsen_tpu.obs import observatory
        # defeat the disk-write throttle so the second publish lands
        monkeypatch.setattr(observatory, "WRITE_INTERVAL_S", 0.0)
        observatory.attach(str(tmp_path))
        try:
            observatory.begin(level_budget=32, rung=(8, 16, 4),
                              segment_iters=4)
            observatory.publish(level=4, frontier=7, segments=1,
                                seg_seconds=0.1, levels_delta=4,
                                expansions=16, dup_rate=0.25, trunc=2)
            observatory.publish(level=8, frontier=5, segments=2,
                                seg_seconds=0.1, levels_delta=4,
                                expansions=16, dup_rate=0.5, trunc=3)
            p = observatory.read_progress(str(tmp_path))
        finally:
            observatory.detach()
        assert p["dup-rate"] == 0.5          # replace semantics
        assert p["trunc-losses"] == 5        # accumulates per rung
        line = observatory.format_status(p)
        assert "dup-rate 50%" in line
        assert "trunc 5" in line

    def test_search_analytics_line(self):
        from jepsen_tpu import cli
        assert cli._search_analytics_line({}) is None
        assert cli._search_analytics_line({"searchstats": None}) is None
        line = cli._search_analytics_line({"searchstats": {
            "levels": 9, "dup-rate": 0.25, "prune-efficiency": 0.5,
            "frontier-area": 40, "frontier-peak": 8,
            "trunc-losses": 2}})
        assert line.startswith("# search:")
        assert "dup-rate 25%" in line
        assert "truncation-losses 2" in line

    def test_bench_search_axes_pick_up_searchstats(self):
        import bench
        axes = bench._search_axes([
            {"searchstats": {"dup-rate": 0.3, "frontier-area": 50,
                             "prune-efficiency": 0.4}},
            {"searchstats": {"dup-rate": 0.1, "frontier-area": 20,
                             "prune-efficiency": 0.2}},
            "not-a-dict",
        ])
        assert axes["dup_rate"] == 0.3
        assert axes["frontier_area"] == 70
        assert axes["prune_efficiency"] == 0.4
        # the rebalance axes are still there (bench_gate reads both)
        assert axes["remesh_count"] == 0
        assert axes["imbalance_ratio"] == 1.0

    def test_bench_gate_attribution_axes(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        assert "search.dup_rate" in bg.ATTRIBUTION_AXES
        assert "search.frontier_area" in bg.ATTRIBUTION_AXES


# ---------------------------------------------------------------------------
# Contention / decomposability profiling (analysis/contention.py)
# ---------------------------------------------------------------------------


class TestContention:
    def test_keyed_disjoint_is_decomposable(self):
        from jepsen_tpu.analysis import contention
        keyed = {k: _hist(20, seed=k, procs=3) for k in range(4)}
        prof = contention.profile(keyed)
        assert prof["decomposable"] is True
        assert prof["decomposability"] >= 0.5
        assert prof["components"] == 4
        assert prof["est-speedup"] > 1.0

    def test_single_key_dense_is_not(self):
        # the acceptance criterion's other half: one dense register
        # history has one conflict component — nothing to decompose
        from jepsen_tpu.analysis import contention
        prof = contention.profile(_hist(60, seed=1, procs=4,
                                        overlap=0.95))
        assert prof["decomposable"] is False
        assert prof["decomposability"] < 0.5
        assert prof["components"] == 1
        assert prof["est-speedup"] == 1.0

    def test_independent_value_convention(self):
        # [key, v] LIST values key the op; a cas (old, new) TUPLE does
        # not (it is payload, not a key)
        from jepsen_tpu.analysis import contention
        h = H((0, "invoke", "write", [0, 1]), (0, "ok", "write", [0, 1]),
              (1, "invoke", "write", [1, 2]), (1, "ok", "write", [1, 2]),
              (2, "invoke", "cas", (1, 2)), (2, "ok", "cas", (1, 2)))
        prof = contention.profile(h)
        # keys 0 and 1, plus the keyless cas in the global component
        assert prof["components"] == 3
        assert prof["keys"] == 2

    def test_concurrency_width(self):
        from jepsen_tpu.analysis import contention
        h = H((0, "invoke", "write", 1), (1, "invoke", "read", None),
              (0, "ok", "write", 1), (1, "ok", "read", 1))
        prof = contention.profile(h)
        assert prof["concurrency"]["max"] == 2
        assert prof["commutativity"]["read-only"] == 1
        assert prof["commutativity"]["mutating"] == 1

    def test_never_raises(self):
        from jepsen_tpu.analysis import contention
        for bad in (None, 42, [], History(), {"k": None}):
            prof = contention.profile(bad)
            assert prof["ops"] == 0
            assert prof["decomposable"] is False
        assert contention.forecast_lines(prof) == \
            ["# contention: unprofilable history"]

    def test_forecast_lines(self):
        from jepsen_tpu.analysis import contention
        keyed = {k: _hist(20, seed=k, procs=3) for k in range(4)}
        lines = contention.forecast_lines(contention.profile(keyed))
        assert all(ln.startswith("# contention:") for ln in lines)
        assert "decomposable" in lines[0]
        assert any("speedup" in ln for ln in lines)


# ---------------------------------------------------------------------------
# jtpu explain (jepsen_tpu/explain.py + CLI + web)
# ---------------------------------------------------------------------------


def _store_run(root, name, history, results, searchstats_dir=None):
    """Materialize a stored run directory the way core.run would."""
    from jepsen_tpu import store
    d = os.path.join(str(root), name, "20260805T120000.000")
    os.makedirs(d, exist_ok=True)
    store.write_history(d, history)
    if results is not None:
        store.write_results(d, results)
    store.write_state(d, "done")
    return d


class TestExplain:
    @pytest.fixture()
    def valid_run(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JTPU_TRACE", "1")
        h = _hist()
        d = _store_run(tmp_path, "reg-valid", h, None)
        obs_searchstats.attach(d)
        try:
            out = T.check_history_tpu(h, CASRegister())
        finally:
            obs_searchstats.detach()
        from jepsen_tpu import store
        store.write_results(d, out)
        return d

    @pytest.fixture()
    def invalid_run(self, tmp_path):
        # a read observes a value never written: non-linearizable
        h = H((0, "invoke", "write", 1), (0, "ok", "write", 1),
              (1, "invoke", "read", None), (1, "ok", "read", 2))
        out = T.check_history_tpu(h, CASRegister())
        assert out["valid"] is False
        return _store_run(tmp_path, "reg-invalid", h, out)

    @pytest.fixture()
    def unknown_run(self, tmp_path, monkeypatch):
        # dense overlap at a pinned tiny rung: the pool truncates live
        # uniques and then dies -> unknown via lossy truncation
        monkeypatch.setenv("JTPU_TRACE", "1")
        h = _hist(60, seed=7, procs=6, overlap=0.98)
        d = _store_run(tmp_path, "reg-unknown", h, None)
        obs_searchstats.attach(d)
        try:
            out = T.check_history_tpu(h, CASRegister(), capacity=2,
                                      window=16, expand=2)
        finally:
            obs_searchstats.detach()
        assert out["valid"] == "unknown" and out["capacity-overflow"]
        from jepsen_tpu import store
        store.write_results(d, out)
        return d

    def test_valid_report(self, valid_run):
        from jepsen_tpu import explain
        rep = explain.explain_report(valid_run)
        assert rep["kind"] == "valid"
        assert rep["searchstats"]["levels"] > 0
        assert rep["frontier-series"]
        text = explain.render_text(rep)
        assert "# explain:" in text
        assert "search shape" in text
        assert "frontier/level" in text

    def test_invalid_report(self, invalid_run):
        from jepsen_tpu import explain
        rep = explain.explain_report(invalid_run)
        assert rep["kind"] == "invalid"
        cex = rep.get("counterexample") or rep.get("counterexample-raw")
        assert cex is not None
        assert cex.get("violating-level") is not None
        text = explain.render_text(rep)
        assert "non-linearizable" in text

    def test_unknown_report_cites_truncation(self, unknown_run):
        from jepsen_tpu import explain
        rep = explain.explain_report(unknown_run)
        assert rep["kind"] == "unknown"
        causes = {c["cause"]: c for c in rep["cause-chain"]}
        assert "lossy-truncation" in causes
        assert causes["lossy-truncation"]["levels"]  # exact levels cited
        text = explain.render_text(rep)
        assert "cause: lossy-truncation" in text

    def test_torn_artifacts_degrade(self, valid_run):
        # a torn searchstats.json and a missing results.json must
        # degrade the report, never crash it (the explain-kill chaos
        # scenario holds the web page to the same contract)
        from jepsen_tpu import explain
        with open(os.path.join(valid_run, "searchstats.json"), "w") as f:
            f.write('{"ts": 1, "levels": [[3,')
        os.unlink(os.path.join(valid_run, "results.json"))
        rep = explain.explain_report(valid_run)
        assert rep["kind"] == "unknown"
        assert any(c["cause"] == "no-verdict"
                   for c in rep["cause-chain"])
        assert "# explain:" in explain.render_text(rep)

    def test_cli_explain(self, valid_run, invalid_run, capsys):
        from jepsen_tpu import cli
        cmds = cli.default_commands()
        assert "explain" in cmds
        rc = cli.run(cmds, ["explain", "--store", valid_run])
        out = capsys.readouterr().out
        assert rc == 0 and "# explain:" in out
        rc = cli.run(cmds, ["explain", "--store", invalid_run,
                            "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 1
        assert json.loads(out)["kind"] == "invalid"
        assert cli.run(cmds, ["explain", "--store",
                              "/no/such/dir"]) == 254

    def test_web_explain_page(self, unknown_run):
        import urllib.request

        from jepsen_tpu import web
        root = os.path.dirname(os.path.dirname(unknown_run))
        rel = os.path.relpath(unknown_run, root)
        server = web.serve_background(root=root)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.server_port}"
                    f"/explain/{rel}", timeout=10) as r:
                assert r.status == 200
                page = r.read().decode()
            assert "# explain:" in page
            assert "lossy-truncation" in page
            # and the home table links to it
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.server_port}/",
                    timeout=10) as r:
                assert f"/explain/{rel}" in r.read().decode()
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# Self-lint: extraction sites live OUTSIDE traced bodies
# ---------------------------------------------------------------------------


class TestLintClean:
    def test_new_surfaces_obey_trace_in_jit(self):
        # every searchstats extraction site is host-side: the kernel
        # writes jnp counters into the carry, and record()/rollup()
        # run at segment barriers (resilience._supervised_check_packed,
        # the one allowlisted body) or after the search returns
        from jepsen_tpu.analysis import jax_lint
        for rel in ("jepsen_tpu/checker/tpu.py",
                    "jepsen_tpu/checker/engine.py",
                    "jepsen_tpu/resilience.py",
                    "jepsen_tpu/obs/searchstats.py",
                    "jepsen_tpu/analysis/contention.py",
                    "jepsen_tpu/explain.py"):
            findings = jax_lint.lint_file(os.path.join(REPO, rel),
                                          root=REPO)
            assert not [f for f in findings
                        if f.rule == "JAX-TRACE-IN-JIT"], rel

    def test_supervised_body_is_the_only_allowlisted_site(self):
        from jepsen_tpu.analysis import jax_lint
        assert ("jepsen_tpu/resilience.py",
                "_supervised_check_packed") \
            in jax_lint.TRACE_IN_JIT_ALLOWLIST
        # the lane itself must NOT need an allowlist entry: searchstats
        # is not a sanctioned obs alias inside traced bodies
        assert "obs_searchstats" not in jax_lint._OBS_ALIASES
