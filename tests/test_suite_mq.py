"""RabbitMQ / hazelcast / galera suite tests against in-process fakes."""

import json
import socketserver
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu import control
from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.history import Op
from jepsen_tpu.suites import galera, hazelcast, rabbitmq

from test_nemesis import dummy_test, logs


def op(f, v=None, p=0):
    return Op(type="invoke", f=f, value=v, process=p, time=0)


# ---------------------------------------------------------------------------
# Fake RabbitMQ management API
# ---------------------------------------------------------------------------


class FakeRabbitHandler(BaseHTTPRequestHandler):
    queues = {}
    lock = threading.Lock()
    drop_publishes = False

    def log_message(self, *a):
        pass

    def _reply(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):  # noqa: N802 — queue declare
        self._reply(201, {})

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(n).decode())
        path = urllib.parse.unquote(self.path)
        with self.lock:
            if path.endswith("/publish"):
                if self.drop_publishes:
                    return self._reply(200, {"routed": False})
                q = self.queues.setdefault(payload["routing_key"], [])
                q.append(payload["payload"])
                return self._reply(200, {"routed": True})
            if path.endswith("/get"):
                qname = path.split("/")[-2]
                q = self.queues.setdefault(qname, [])
                if not q:
                    return self._reply(200, [])
                return self._reply(200, [{"payload": q.pop(0)}])
        self._reply(404, {})


@pytest.fixture()
def fake_rabbit():
    FakeRabbitHandler.queues = {}
    FakeRabbitHandler.drop_publishes = False
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeRabbitHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"127.0.0.1:{server.server_port}"
    server.shutdown()


class TestRabbitQueueClient:
    def test_enqueue_dequeue_roundtrip(self, fake_rabbit):
        c = rabbitmq.QueueClient().open({}, fake_rabbit)
        assert c.invoke({}, op("enqueue", 41)).type == "ok"
        got = c.invoke({}, op("dequeue"))
        assert got.type == "ok" and got.value == 41
        assert c.invoke({}, op("dequeue")).type == "fail"

    def test_unrouted_publish_fails(self, fake_rabbit):
        FakeRabbitHandler.drop_publishes = True
        c = rabbitmq.QueueClient().open({}, fake_rabbit)
        assert c.invoke({}, op("enqueue", 1)).type == "fail"

    def test_drain_writes_history(self, fake_rabbit):
        from jepsen_tpu.history import History
        c = rabbitmq.QueueClient().open({}, fake_rabbit)
        for v in (1, 2):
            c.invoke({}, op("enqueue", v))
        hist = History()
        test = {"_history_lock": threading.Lock(),
                "_active_histories": [hist]}
        out = c.invoke(test, op("drain", p=2))
        assert out.value == "exhausted"
        assert [o.value for o in hist if o.is_ok] == [1, 2]

    def test_down_broker(self):
        c = rabbitmq.QueueClient(timeout=0.3).open({}, "127.0.0.1:1")
        assert c.invoke({}, op("enqueue", 1)).type == "info"
        # dequeue transport errors are indeterminate: the mgmt-API get acks
        # the message server-side before the response arrives, so a lost
        # response may have consumed a message we never observed
        assert c.invoke({}, op("dequeue")).type == "info"

    def test_semaphore_token_cycle(self, fake_rabbit):
        a = rabbitmq.SemaphoreClient().open({"nodes": []}, fake_rabbit)
        b = rabbitmq.SemaphoreClient().open({"nodes": []}, fake_rabbit)
        assert a.invoke({}, op("acquire")).type == "ok"
        assert b.invoke({}, op("acquire")).type == "fail"  # token taken
        assert a.invoke({}, op("release")).type == "ok"
        assert b.invoke({}, op("acquire")).type == "ok"


# ---------------------------------------------------------------------------
# Fake hazelcast shim
# ---------------------------------------------------------------------------


class FakeShim(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        class H(socketserver.StreamRequestHandler):
            def handle(hs):
                while True:
                    line = hs.rfile.readline()
                    if not line:
                        return
                    hs.wfile.write(
                        (self.dispatch(line.decode().split()) + "\n")
                        .encode())
                    hs.wfile.flush()

        super().__init__(("127.0.0.1", 0), H)
        self.locks = {}
        self.ids = 0
        self.maps = {}
        self.queues = {}
        self.lock = threading.Lock()

    def dispatch(self, t):
        with self.lock:
            if t[0] == "LOCK":
                if self.locks.get(t[1]):
                    return "FAIL"
                self.locks[t[1]] = True
                return "OK"
            if t[0] == "UNLOCK":
                if not self.locks.get(t[1]):
                    return "FAIL"
                self.locks[t[1]] = False
                return "OK"
            if t[0] == "ID":
                self.ids += 1
                return str(self.ids)
            if t[0] == "MAPGET":
                return self.maps.get((t[1], t[2]), "NIL")
            if t[0] == "MAPPUT":
                self.maps[(t[1], t[2])] = t[3]
                return "OK"
            if t[0] == "MAPCAS":
                cur = self.maps.get((t[1], t[2]), "NIL")
                if cur != t[3]:
                    return "FAIL"
                self.maps[(t[1], t[2])] = t[4]
                return "OK"
            if t[0] == "QOFFER":
                self.queues.setdefault(t[1], []).append(t[2])
                return "OK"
            if t[0] == "QPOLL":
                q = self.queues.setdefault(t[1], [])
                return q.pop(0) if q else "NIL"
            return "ERR"


@pytest.fixture()
def fake_shim():
    server = FakeShim()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"127.0.0.1:{server.server_address[1]}"
    server.shutdown()


class TestHazelcastWorkloads:
    def test_lock_client(self, fake_shim):
        a = hazelcast.LockClient().open({}, fake_shim)
        b = hazelcast.LockClient().open({}, fake_shim)
        assert a.invoke({}, op("acquire")).type == "ok"
        assert b.invoke({}, op("acquire")).type == "fail"
        assert a.invoke({}, op("release")).type == "ok"
        assert b.invoke({}, op("acquire")).type == "ok"

    def test_id_clients_unique(self, fake_shim):
        c = hazelcast.IdClient().open({}, fake_shim)
        ids = [c.invoke({}, op("generate")).value for _ in range(10)]
        assert len(set(ids)) == 10

    def test_map_add_read(self, fake_shim):
        c = hazelcast.MapClient().open({}, fake_shim)
        for v in (3, 1, 2):
            assert c.invoke({}, op("add", v)).type == "ok"
        got = c.invoke({}, op("read"))
        assert got.value == [1, 2, 3]

    def test_queue_client(self, fake_shim):
        c = hazelcast.HZQueueClient().open({}, fake_shim)
        assert c.invoke({}, op("enqueue", 5)).type == "ok"
        assert c.invoke({}, op("dequeue")).value == 5
        assert c.invoke({}, op("drain")).type == "fail"

    def test_registry_structure(self):
        w = hazelcast.workloads()
        assert set(w) == {"crdt-map", "map", "lock", "queue",
                          "atomic-ref-ids", "atomic-long-ids",
                          "id-gen-ids"}
        t = hazelcast.hazelcast_test({"workload": "lock",
                                      "time-limit": 1})
        assert t["name"] == "hazelcast-lock"

    def test_down_shim(self):
        c = hazelcast.LockClient(timeout=0.3).open({}, "127.0.0.1:1")
        assert c.invoke({}, op("acquire")).type == "info"


class TestGalera:
    def test_dirty_reads_checker(self):
        H = [op("write", 1).replace(type="fail"),
             op("read").replace(type="ok", value=[1, 1]),
             op("read").replace(type="ok", value=[2, 3])]
        out = galera.DirtyReadsChecker().check({}, H)
        assert out["valid"] is False
        assert out["dirty-reads"] == [[1, 1]]
        assert out["inconsistent-reads"] == [[2, 3]]

    def test_dirty_reads_checker_clean(self):
        H = [op("write", 1).replace(type="ok"),
             op("read").replace(type="ok", value=[1, 1])]
        out = galera.DirtyReadsChecker().check({}, H)
        assert out["valid"] is True

    def test_write_txn_sql_shape(self):
        t = dummy_test()
        with control.session_pool(t):
            c = galera.DirtyReadsClient(2).open(t, "n1")
            assert c.invoke(t, op("write", 7)).type == "ok"
            stmt = next(cmd for cmd in logs(t)["n1"] if "UPDATE" in cmd)
            assert "SERIALIZABLE" in stmt and "BEGIN" in stmt
            assert "SET x = 7" in stmt and "COMMIT" in stmt

    def test_read_parses(self):
        t = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "SELECT x FROM dirty": "3\n3\n"}}})
        with control.session_pool(t):
            c = galera.DirtyReadsClient(2).open(t, "n1")
            got = c.invoke(t, op("read"))
            assert got.type == "ok" and got.value == [3, 3]
