"""The static-analysis subsystem (jepsen_tpu.analysis): per-pass unit
tests over synthetic good/bad fixtures, the pre-search history gate,
the shared op-type validation, the baseline machinery, and a self-lint
asserting the repo is clean against its committed baseline. All tier-1
(marker: lint)."""

import io
import json
import os
import sys

import pytest

from jepsen_tpu import analysis, cli
from jepsen_tpu.analysis import baseline as bl
from jepsen_tpu.analysis import history_lint as hl
from jepsen_tpu.analysis.opcheck import (INVALID_TYPE_FLAG,
                                         VALID_OP_TYPES, invalid_op_type)
from jepsen_tpu.history import History, Op, VALID_TYPES

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures", "lint")


def _rules(findings):
    return {f.rule for f in findings}


def _lint(path, **kw):
    return analysis.lint_files([os.path.join(FIX, path)], **kw)


# ---------------------------------------------------------------------------
# Pass 1: suite linter
# ---------------------------------------------------------------------------

class TestSuiteLint:
    def test_bad_suite_fixture_fires_every_rule(self):
        fs = _lint("bad_suite.py")
        assert {"SUITE-OP-TYPE", "SUITE-OP-NO-F",
                "SUITE-CLIENT-NO-INVOKE",
                "SUITE-BLOCKING-NO-TIMEOUT"} <= _rules(fs)
        # findings carry file:line
        assert all(f.path.endswith("bad_suite.py") and f.line > 0
                   for f in fs)

    def test_good_suite_fixture_is_clean(self):
        assert _lint("good_suite.py") == []

    def test_blocking_call_reached_through_self_helper(self):
        fs = [f for f in _lint("bad_suite.py")
              if f.rule == "SUITE-BLOCKING-NO-TIMEOUT"]
        # one direct (urlopen in invoke), one via self._rpc
        assert len(fs) == 2

    def test_registry_cross_check(self):
        from jepsen_tpu.analysis import suite_lint
        paths = [os.path.join(FIX, "bad_suite.py"),
                 os.path.join(FIX, "good_suite.py")]
        reg = {"fine": ("good_suite", "fine_test"),
               "broken": ("bad_suite", "broken_test"),
               "missing-attr": ("good_suite", "no_such_ctor"),
               "missing-mod": ("no_such_module", "x_test")}
        fs = suite_lint.lint_suites(paths, registry=reg)
        assert "SUITE-CTOR-ARITY" in _rules(fs)          # broken_test
        missing = [f for f in fs if f.rule == "SUITE-REGISTRY-MISSING"]
        assert len(missing) == 2                          # attr + module

    def test_real_registry_resolves_statically(self):
        # the real SUITES registry must produce no registry findings
        fs = analysis.lint_repo(passes=("suite",))
        assert "SUITE-REGISTRY-MISSING" not in _rules(fs)
        assert "SUITE-CTOR-ARITY" not in _rules(fs)


# ---------------------------------------------------------------------------
# Pass 2: history linter + the pre-search gate
# ---------------------------------------------------------------------------

class TestHistoryLint:
    def test_bad_history_fixture_fires_every_rule(self):
        fs = _lint("bad_history.jsonl")
        assert {"HIST-DECODE", "HIST-DANGLING-INVOKE", "HIST-PROC-REUSE",
                "HIST-UNMATCHED-COMPLETE", "HIST-OP-TYPE",
                "HIST-INDEX-ORDER"} <= _rules(fs)

    def test_good_history_fixture_has_no_errors(self):
        fs = _lint("good_history.jsonl")
        assert hl.errors(fs) == []
        # the crashed op surfaces as a note, not damage
        assert "HIST-OPEN-INVOKE" in _rules(fs)

    def test_crashed_op_is_legal(self):
        h = History.of([
            Op(type="invoke", f="write", value=1, process=0, time=0),
            Op(type="ok", f="write", value=1, process=0, time=1),
            Op(type="invoke", f="write", value=2, process=1, time=2),
        ])
        assert hl.errors(hl.lint_history(h)) == []

    def test_nemesis_ops_never_pair(self):
        h = History.of([
            Op(type="info", f="start", process="nemesis", time=0),
            Op(type="info", f="stop", process="nemesis", time=1),
            Op(type="info", f="heal-verified", process="nemesis", time=2),
        ])
        assert hl.lint_history(h) == []

    def test_f_mismatch_between_pairs(self):
        h = History.of([
            Op(type="invoke", f="write", value=1, process=0, time=0),
            Op(type="ok", f="read", value=1, process=0, time=1),
        ])
        assert "HIST-F-MISMATCH" in _rules(hl.lint_history(h))

    def test_gate_rejects_with_rule_id_before_any_jit(self, monkeypatch):
        from jepsen_tpu.checker import tpu
        from jepsen_tpu.models import CASRegister

        def boom(*a, **k):  # any compilation attempt is a failure
            raise AssertionError("jit factory invoked for a "
                                 "malformed history")

        monkeypatch.setattr(tpu, "_jit_single", boom)
        monkeypatch.setattr(tpu, "_jit_segment", boom)
        monkeypatch.setattr(tpu, "_jit_batch", boom)
        bad = History.of([
            Op(type="invoke", f="write", value=1, process=0, time=0),
            Op(type="invoke", f="read", value=None, process=0, time=1),
            Op(type="ok", f="read", value=1, process=0, time=2),
        ])
        with pytest.raises(hl.MalformedHistoryError) as ei:
            tpu.check_history_tpu(bad, CASRegister())
        assert "HIST-DANGLING-INVOKE" in str(ei.value)

    def test_gate_surfaces_through_check_safe(self):
        from jepsen_tpu.checker import check_safe
        from jepsen_tpu.checker.wgl import linearizable
        from jepsen_tpu.models import CASRegister
        bad = History.of([
            Op(type="ok", f="read", value=1, process=0, time=0),
        ])
        out = check_safe(linearizable(CASRegister(), backend="tpu"),
                         {}, bad)
        assert out["valid"] == "unknown"
        assert "HIST-UNMATCHED-COMPLETE" in out["error"]

    def test_gate_kill_switch(self, monkeypatch):
        monkeypatch.setenv("JTPU_HISTORY_GATE", "0")
        bad = History.of([
            Op(type="ok", f="read", value=1, process=0, time=0),
        ])
        assert hl.gate_history(bad) == []

    def test_keyed_gate_isolates_the_malformed_key(self):
        from jepsen_tpu.checker.tpu import check_keyed_tpu
        from jepsen_tpu.models import CASRegister
        good = [Op(type="invoke", f="write", value=1, process=0, time=0),
                Op(type="ok", f="write", value=1, process=0, time=1)]
        bad = [Op(type="ok", f="read", value=1, process=0, time=0)]
        out = check_keyed_tpu({"g": History.of(good),
                               "b": History.of(bad)}, CASRegister())
        assert out["results"]["g"]["valid"] is True
        assert out["results"]["b"]["valid"] == "unknown"
        assert out["results"]["b"]["lint"] == {
            "HIST-UNMATCHED-COMPLETE": 1}
        assert out["valid"] == "unknown"


class TestSharedOpValidation:
    def test_one_validation_function(self):
        # the runtime guard and the lint rule share the same notion
        assert tuple(VALID_TYPES) == VALID_OP_TYPES
        for t in VALID_OP_TYPES:
            assert invalid_op_type(t) is None
        assert invalid_op_type("okk")

    def test_from_dict_tolerates_and_flags(self):
        op = Op.from_dict({"type": "okk", "f": "read", "process": 0})
        assert op.type == "okk"  # tolerated
        assert INVALID_TYPE_FLAG in op.extra  # flagged

    def test_from_jsonl_counts_type_errors(self):
        h = History.from_jsonl(
            '{"type": "invoke", "f": "read", "process": 0}\n'
            '{"type": "okk", "f": "read", "process": 0}\n')
        assert len(h) == 2 and h.type_errors == 1
        assert "HIST-OP-TYPE" in _rules(hl.lint_history(h))

    def test_clean_roundtrip_unchanged(self):
        d = {"type": "ok", "f": "read", "value": 3, "process": 0,
             "time": 5, "index": 2}
        assert Op.from_dict(d).to_dict() == d


# ---------------------------------------------------------------------------
# Pass 3: JAX hazard linter
# ---------------------------------------------------------------------------

class TestJaxLint:
    def test_bad_jax_fixture_fires_every_rule(self):
        fs = _lint("bad_jax.py")
        assert {"JAX-HOST-SYNC", "JAX-HOST-CAST",
                "JAX-UNHASHABLE-STATIC", "JAX-INT32-OVERFLOW",
                "JAX-SHIFT-WIDTH"} <= _rules(fs)

    def test_call_closure_reaches_named_helpers(self):
        fs = [f for f in _lint("bad_jax.py")
              if f.rule == "JAX-HOST-SYNC" and "helper" in f.message]
        assert fs, "np call in a loop-body helper must be flagged"

    def test_good_jax_fixture_is_clean(self):
        # trace-time numpy in a host-side builder is idiom, not hazard
        # (including the locally-shadowed module width in pack_shadowed)
        assert _lint("good_jax.py") == []

    def test_named_constant_folding(self):
        # the overflow/shift rules fold module-level named constants,
        # not just literals (pack_named in the bad fixture)
        fs = [f for f in _lint("bad_jax.py") if "pack_named" in f.anchor]
        assert {f.rule for f in fs} == {"JAX-SHIFT-WIDTH",
                                        "JAX-INT32-OVERFLOW"}
        assert len([f for f in fs
                    if f.rule == "JAX-INT32-OVERFLOW"]) == 2

    def test_imported_constant_resolves_through_repo_module(self):
        # RET_INF comes from jepsen_tpu/ops/encode.py: the width chain
        # crosses a module boundary and still folds
        fs = [f for f in _lint("bad_jax.py")
              if f.rule == "JAX-INT32-OVERFLOW"
              and "2147483648" in f.message]
        assert fs, "np.int32(RET_INF + 1) must fold via the import"

    def test_shadowed_name_does_not_fold(self):
        from jepsen_tpu.analysis import jax_lint
        import ast
        tree = ast.parse(
            "W = 40\n"
            "def f(v, n):\n"
            "    W = n & 7\n"
            "    return v << W\n"
            "def g(v):\n"
            "    return v << W\n")
        shadows = jax_lint._shadow_sets(tree)
        env = jax_lint._module_env(tree, None)
        assert env == {"W": 40}
        shifts = [n for n in ast.walk(tree)
                  if isinstance(n, ast.BinOp)
                  and isinstance(n.op, ast.LShift)]
        shadowed = [n for n in shifts
                    if "W" in shadows.get(id(n), ())]
        assert len(shadowed) == 1  # f's shift only; g's folds to 40


# ---------------------------------------------------------------------------
# SARIF export (shared findings core)
# ---------------------------------------------------------------------------

class TestSarif:
    def test_sarif_document_shape(self):
        from jepsen_tpu.analysis import sarif
        fs = _lint("bad_jax.py")
        assert fs
        doc = sarif.to_sarif(fs)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert rule_ids == {f.rule for f in fs}
        assert len(run["results"]) == len(fs)
        r0 = run["results"][0]
        assert r0["level"] in ("error", "warning", "note")
        loc = r0["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad_jax.py")
        assert loc["region"]["startLine"] >= 1

    def test_sarif_fingerprints_are_baseline_anchors(self):
        from jepsen_tpu.analysis import sarif
        fs = _lint("bad_jax.py")
        doc = sarif.to_sarif(fs)
        prints = [r["partialFingerprints"]["jtpuAnchor/v1"]
                  for r in doc["runs"][0]["results"]]
        assert sorted(prints) == sorted(f.anchor for f in fs)

    def test_sarif_render_round_trips(self):
        from jepsen_tpu.analysis import sarif
        text = sarif.render(_lint("bad_lockset.py"))
        doc = json.loads(text)
        assert doc["$schema"].endswith("sarif-schema-2.1.0.json")

    def test_lint_gate_sarif_flag(self, tmp_path):
        import subprocess
        import sys as _sys
        out = tmp_path / "lint.sarif"
        r = subprocess.run(
            [_sys.executable, os.path.join(REPO, "tools",
                                           "lint_gate.py"),
             "--sarif", str(out), "--no-plan"],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(out.read_text())
        assert doc["version"] == "2.1.0"
        assert doc["runs"][0]["results"] == []  # clean vs baseline


# ---------------------------------------------------------------------------
# Pass 4: lockset linter
# ---------------------------------------------------------------------------

class TestLocksetLint:
    def test_bad_lockset_fixture(self):
        fs = _lint("bad_lockset.py")
        assert {"LOCK-UNGUARDED", "LOCK-LIFECYCLE"} <= _rules(fs)
        # guarded accesses and plain initialization are NOT flagged
        lines = {f.line for f in fs}
        assert all(line >= 14 for line in lines), \
            "conj_op_ok's guarded accesses were wrongly flagged"

    def test_core_conj_op_is_clean(self):
        fs = analysis.lint_files(["jepsen_tpu/core.py"],
                                 passes=("lockset",))
        assert all("conj_op" not in f.anchor for f in fs)
        assert not [f for f in fs if f.severity == "error"]

    def test_bad_class_fixture_fires_every_rule(self):
        fs = _lint("bad_lockset_class.py", passes=("lockset",))
        assert _rules(fs) == {"LOCK-UNGUARDED", "LOCK-INCONSISTENT",
                              "LOCK-LIFECYCLE"}
        by_rule = {f.rule: f for f in fs}
        # the off-lock mutation is the error; the lifecycle read and
        # the wrong-lock access are downgraded to warnings
        assert by_rule["LOCK-UNGUARDED"].severity == "error"
        assert "racy_incr" in by_rule["LOCK-UNGUARDED"].anchor
        assert by_rule["LOCK-LIFECYCLE"].severity == "warning"
        assert "stop" in by_rule["LOCK-LIFECYCLE"].anchor
        assert "_aux" in by_rule["LOCK-INCONSISTENT"].message

    def test_good_class_fixture_is_clean(self):
        # consistent locking + a '# guarded-by: none' opt-out: no
        # findings, including no LOCK-LIFECYCLE noise
        assert _lint("good_lockset_class.py", passes=("lockset",)) == []


# ---------------------------------------------------------------------------
# Pass 6: deadlock linter
# ---------------------------------------------------------------------------

class TestDeadlockLint:
    def test_cycle_fixture_fires(self):
        fs = _lint("bad_deadlock.py", passes=("deadlock",))
        assert _rules(fs) == {"LOCK-ORDER-CYCLE", "LOCK-HELD-BLOCKING"}
        cyc = [f for f in fs if f.rule == "LOCK-ORDER-CYCLE"]
        assert len(cyc) == 1 and cyc[0].severity == "error"
        # both locks named, with the witnessing call-site edges
        assert "Left._lock" in cyc[0].message
        assert "Right._lock" in cyc[0].message
        assert "poke() calls touch()" in cyc[0].message
        blk = [f for f in fs if f.rule == "LOCK-HELD-BLOCKING"]
        assert len(blk) == 1 and "os.fsync" in blk[0].message

    def test_diamond_lock_order_is_clean(self):
        # two paths through a diamond (top -> left|right -> bottom)
        # converge without reversing an edge: acyclic, no findings
        assert _lint("good_deadlock.py", passes=("deadlock",)) == []


# ---------------------------------------------------------------------------
# Pass 7: crash-consistency (walcheck) linter
# ---------------------------------------------------------------------------

class TestWalcheckLint:
    def test_bad_fixture_fires_every_rule(self):
        fs = _lint("bad_walcheck.py", passes=("walcheck",))
        assert _rules(fs) == {"WAL-ACK-BEFORE-JOURNAL",
                              "ATOMIC-WRITE-DIRECT",
                              "ATOMIC-TMP-SCANNED"}
        wal = [f for f in fs if f.rule == "WAL-ACK-BEFORE-JOURNAL"]
        # both shapes: the unjournaled 202 ack AND the 'done' record
        # journaled before the artifact's os.replace
        assert any("202" in f.message for f in wal)
        assert any("'done'" in f.message for f in wal)

    def test_good_fixture_is_clean(self):
        # journal-before-ack (with the replay-arm and duplicate-re-ack
        # exemptions exercised) + dot-prefixed tmp + os.replace: clean
        assert _lint("good_walcheck.py", passes=("walcheck",)) == []


# ---------------------------------------------------------------------------
# Baseline + CLI + self-lint
# ---------------------------------------------------------------------------

class TestBaseline:
    def test_roundtrip_and_split(self, tmp_path):
        fs = _lint("bad_lockset.py")
        assert fs
        p = tmp_path / "lint.baseline"
        bl.write(str(p), fs)
        loaded = bl.load(str(p))
        assert len(loaded) == len({f.key() for f in fs})
        new, accepted = bl.split(fs, loaded)
        assert new == [] and len(accepted) == len(fs)

    def test_justifications_survive_rewrite(self, tmp_path):
        fs = _lint("bad_lockset.py")
        p = tmp_path / "lint.baseline"
        key = fs[0].key()
        p.write_text(f"{key} — because reasons\n")
        bl.write(str(p), fs)
        assert bl.load(str(p))[key] == "because reasons"

    def test_committed_baseline_entries_are_justified(self):
        for key, just in bl.load().items():
            assert just and "TODO" not in just, \
                f"baseline entry {key!r} lacks a real justification"

    def test_stubbed_reports_todo_and_empty_justifications(self):
        base = {"A f.py#x": "real reason",
                "B g.py#y": bl.STUB,
                "C h.py#z": ""}
        assert bl.stubbed(base) == ["B g.py#y", "C h.py#z"]

    def test_strict_rejects_stub_justifications(self, tmp_path):
        """--write-baseline stubs must be filled in before --strict
        treats the entry as a real acceptance."""
        import contextlib
        target = os.path.join(FIX, "bad_lockset.py")
        p = tmp_path / "b.baseline"
        rc, _ = _run_cli(["lint", "--baseline", str(p),
                          "--write-baseline", target])
        assert rc == cli.OK
        assert bl.STUB in p.read_text()
        # non-strict: the stubbed acceptance still suppresses
        rc, _ = _run_cli(["lint", "--baseline", str(p), target])
        assert rc == cli.OK
        # strict: refused, with a clear per-entry message
        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            rc, _ = _run_cli(["lint", "--strict",
                              "--baseline", str(p), target])
        assert rc == cli.TEST_FAILED
        assert "stub justification" in err.getvalue()
        assert str(p) in err.getvalue()
        # a real justification clears the gate
        p.write_text(p.read_text().replace(
            bl.STUB, "reviewed: fixture intentionally racy"))
        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            rc, _ = _run_cli(["lint", "--strict",
                              "--baseline", str(p), target])
        assert rc == cli.OK, err.getvalue()
        assert "stub justification" not in err.getvalue()


def _run_cli(argv):
    buf = io.StringIO()
    old = sys.stdout
    sys.stdout = buf
    try:
        rc = cli.run(cli.default_commands(), argv)
    finally:
        sys.stdout = old
    return rc, buf.getvalue()


class TestLintCLI:
    def test_bad_fixtures_exit_nonzero_with_location_and_rule(self):
        for fixture in ("bad_suite.py", "bad_jax.py", "bad_lockset.py",
                        "bad_history.jsonl"):
            rc, out = _run_cli(["lint", os.path.join(FIX, fixture)])
            assert rc == cli.TEST_FAILED, fixture
            assert fixture + ":" in out and "[" in out, fixture

    def test_good_fixtures_exit_zero(self):
        rc, out = _run_cli(["lint", os.path.join(FIX, "good_suite.py"),
                            os.path.join(FIX, "good_jax.py"),
                            os.path.join(FIX, "good_history.jsonl")])
        assert rc == cli.OK
        # the only finding is the legal crashed op's note — no gate
        assert ": error:" not in out and ": warning:" not in out
        assert "HIST-OPEN-INVOKE" in out

    def test_missing_path_is_not_clean(self):
        rc, out = _run_cli(["lint", "no/such/file.py"])
        assert rc == cli.TEST_FAILED
        assert "LINT-MISSING-FILE" in out

    def test_json_format(self):
        rc, out = _run_cli(["lint", "--format", "json",
                            os.path.join(FIX, "bad_history.jsonl")])
        assert rc == cli.TEST_FAILED
        doc = json.loads(out)
        assert doc["counts"]["HIST-PROC-REUSE"] == 1

    def test_write_baseline_then_clean(self, tmp_path):
        p = tmp_path / "b.baseline"
        target = os.path.join(FIX, "bad_lockset.py")
        rc, _ = _run_cli(["lint", "--baseline", str(p),
                          "--write-baseline", target])
        assert rc == cli.OK
        rc, out = _run_cli(["lint", "--baseline", str(p), target])
        assert rc == cli.OK and "accepted" in out

    def test_prune_stale_drops_fixed_entries_only(self, tmp_path):
        p = tmp_path / "b.baseline"
        target = os.path.join(FIX, "bad_lockset.py")
        rc, _ = _run_cli(["lint", "--baseline", str(p),
                          "--write-baseline", target])
        assert rc == cli.OK
        # justify the live entries, then plant one stale entry
        p.write_text(p.read_text().replace(
            bl.STUB, "reviewed: fixture intentionally racy"))
        stale_key = "LOCK-UNGUARDED gone.py#fixed/x"
        with open(p, "a", encoding="utf-8") as f:
            f.write(f"{stale_key} — was fixed long ago\n")
        rc, out = _run_cli(["lint", "--baseline", str(p),
                            "--prune-stale", target])
        assert rc == cli.OK
        assert stale_key in out and "1 stale baseline entry pruned" in out
        loaded = bl.load(str(p))
        assert stale_key not in loaded
        # survivors keep their justifications verbatim
        assert loaded and all(j == "reviewed: fixture intentionally racy"
                              for j in loaded.values())
        # a second prune is a no-op
        rc, out = _run_cli(["lint", "--baseline", str(p),
                            "--prune-stale", target])
        assert rc == cli.OK and "0 stale baseline entries pruned" in out

    def test_self_lint_repo_clean_against_committed_baseline(self):
        # the acceptance gate: all four passes over the live tree,
        # exit 0 against lint.baseline
        rc, out = _run_cli(["lint"])
        assert rc == cli.OK, out
        assert "# lint: clean" in out

    def test_lint_gate_tool_is_clean(self):
        import subprocess
        pr = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_gate.py")],
            capture_output=True, text=True, timeout=120)
        assert pr.returncode == 0, pr.stdout + pr.stderr
        assert "clean against the baseline" in pr.stdout
        assert "stale baseline entry" not in pr.stdout

    def test_lint_gate_stale_escalation(self, tmp_path):
        """A stale baseline entry warns for --stale-grace runs (sidecar
        counter), then FAILS the gate until pruned; the prune clears
        the escalation and the next clean run removes the sidecar."""
        import shutil
        import subprocess
        p = tmp_path / "lint.baseline"
        shutil.copyfile(os.path.join(REPO, "lint.baseline"), str(p))
        stale_key = "LOCK-UNGUARDED gone.py#fixed/x"
        with open(p, "a", encoding="utf-8") as f:
            f.write(f"{stale_key} — was fixed long ago\n")
        cmd = [sys.executable,
               os.path.join(REPO, "tools", "lint_gate.py"),
               "--baseline", str(p), "--no-plan", "--stale-grace", "1"]

        r1 = subprocess.run(cmd, capture_output=True, text=True,
                            timeout=120)
        assert r1.returncode == 0, r1.stdout + r1.stderr
        assert "stale baseline entry" in r1.stdout
        assert "[1/1 warning(s)]" in r1.stdout
        assert (tmp_path / "lint.baseline.stale").exists()

        r2 = subprocess.run(cmd, capture_output=True, text=True,
                            timeout=120)
        assert r2.returncode == 1, r2.stdout + r2.stderr
        assert "stale past the 1-run grace" in r2.stderr
        assert "--prune-stale" in r2.stderr

        rc, out = _run_cli(["lint", "--baseline", str(p),
                            "--prune-stale"])
        assert rc == cli.OK and stale_key in out

        r3 = subprocess.run(cmd, capture_output=True, text=True,
                            timeout=120)
        assert r3.returncode == 0, r3.stdout + r3.stderr
        assert "stale baseline entry" not in r3.stdout
        assert not (tmp_path / "lint.baseline.stale").exists()


class TestRecoverPathGate:
    def test_recover_fails_on_structurally_damaged_wal(self, tmp_path):
        """A WAL whose mid-stream completion record was lost (CRC
        corruption) leaves a process reusing itself; recovery must fail
        with a lint diagnostic instead of checking the damaged
        history."""
        import contextlib

        from jepsen_tpu import journal, store
        d = tmp_path / "run"
        d.mkdir()
        ops = [
            Op(type="invoke", f="write", value=1, process=0, time=0),
            # the ok completion for process 0 was here — corrupted away
            Op(type="invoke", f="read", value=None, process=0, time=2),
            Op(type="ok", f="read", value=1, process=0, time=3),
        ]
        with open(d / "history.wal", "wb") as f:
            for o in ops:
                f.write(journal.encode_record(o))
        store.write_state(str(d), "running")
        # fake a dead recorder
        st = store.read_state(str(d))
        st["pid"] = 2 ** 22 + 12345  # vanishingly unlikely to be alive
        import json as _json
        (d / "run.state").write_text(_json.dumps(st))
        assert store.run_status(str(d)) == "dead"

        buf, err = io.StringIO(), io.StringIO()
        with contextlib.redirect_stdout(buf), \
                contextlib.redirect_stderr(err):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store", str(d)])
        assert rc == cli.TEST_FAILED
        assert "# lint:" in buf.getvalue()
        assert "HIST-DANGLING-INVOKE" in buf.getvalue() + err.getvalue()
        # no results.json: the checker never ran on damaged structure
        assert not (d / "results.json").exists()

    def test_analyze_prints_lint_summary(self, tmp_path):
        import contextlib
        d = tmp_path / "run"
        d.mkdir()
        h = History.of([
            Op(type="invoke", f="write", value=1, process=0, time=0),
            Op(type="ok", f="write", value=1, process=0, time=1),
        ]).index()
        (d / "history.jsonl").write_text(h.to_jsonl() + "\n")
        (d / "test.json").write_text('{"name": "t"}')
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run(cli.default_commands(),
                         ["analyze", "--store", str(d)])
        assert rc == cli.OK
        assert "# lint: clean" in buf.getvalue()
