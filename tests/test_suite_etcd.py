"""etcd suite tests: DB lifecycle against the dummy control plane, and the
real HTTP client + full canonical test against an in-process fake etcd
speaking the v2 keys API."""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu import control, core
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.suites import etcd

from test_nemesis import dummy_test, logs


class TestEtcdDB:
    def test_setup_installs_and_starts(self):
        t = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "stat ": (1, "", "nope"), "ls -A": "etcd-v3.1.5-linux-amd64",
            "dirname": "/opt"}}})
        with control.session_pool(t):
            db = etcd.EtcdDB()
            db.setup(t, "n1")
            cmds = logs(t)["n1"]
            assert any("wget" in c and "etcd-v3.1.5-linux-amd64.tar.gz" in c
                       for c in cmds)
            start = next(c for c in cmds if "start-stop-daemon" in c)
            assert "--name n1" in start
            assert ("--initial-cluster n1=http://n1:2380,n2=http://n2:2380"
                    in start)
            assert "--advertise-client-urls http://n1:2379" in start

    def test_teardown_stops_and_wipes(self):
        t = dummy_test()
        with control.session_pool(t):
            etcd.EtcdDB().teardown(t, "n1")
            cmds = logs(t)["n1"]
            assert any("killall -9 -w etcd" in c for c in cmds)
            assert any("rm -rf /opt/etcd/default.etcd" in c for c in cmds)

    def test_log_files(self):
        assert etcd.EtcdDB().log_files({}, "n1") == ["/opt/etcd/etcd.log"]


class FakeEtcdHandler(BaseHTTPRequestHandler):
    """Minimal etcd v2 /v2/keys implementation over a lock-guarded dict."""

    store = {}
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def _key(self):
        return urllib.parse.unquote(
            urllib.parse.urlparse(self.path).path[len("/v2/keys/"):])

    def _reply(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        k = self._key()
        with self.lock:
            if k not in self.store:
                return self._reply(404, {"errorCode": 100})
            return self._reply(200, {"node": {"value":
                                              str(self.store[k])}})

    def do_PUT(self):  # noqa: N802
        k = self._key()
        n = int(self.headers.get("Content-Length", 0))
        form = dict(urllib.parse.parse_qsl(self.rfile.read(n).decode()))
        with self.lock:
            if "prevValue" in form:
                if k not in self.store:
                    return self._reply(404, {"errorCode": 100})
                if str(self.store[k]) != form["prevValue"]:
                    return self._reply(412, {"errorCode": 101})
            self.store[k] = form["value"]
            return self._reply(200, {"node": {"value": form["value"]}})


@pytest.fixture()
def fake_etcd():
    FakeEtcdHandler.store = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeEtcdHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"127.0.0.1:{server.server_port}"
    server.shutdown()


class TestEtcdClient:
    def test_write_read_cas(self, fake_etcd):
        c = etcd.EtcdClient().open({}, fake_etcd)

        def op(f, v):
            from jepsen_tpu.history import Op
            return Op(type="invoke", f=f,
                      value=independent.tuple_(0, v), process=0, time=0)

        assert c.invoke({}, op("read", None)).type == "fail"  # not found
        assert c.invoke({}, op("write", 3)).type == "ok"
        got = c.invoke({}, op("read", None))
        assert got.type == "ok" and got.value.value == 3
        assert c.invoke({}, op("cas", (3, 4))).type == "ok"
        assert c.invoke({}, op("cas", (3, 9))).type == "fail"
        got = c.invoke({}, op("read", None))
        assert got.value.value == 4

    def test_connection_refused_crashes_appropriately(self):
        c = etcd.EtcdClient(timeout=0.3).open({}, "127.0.0.1:1")
        from jepsen_tpu.history import Op

        def op(f, v):
            return Op(type="invoke", f=f,
                      value=independent.tuple_(0, v), process=0, time=0)
        assert c.invoke({}, op("read", None)).type == "fail"
        assert c.invoke({}, op("write", 1)).type == "info"


class TestCanonicalEtcdTest:
    def test_full_run_against_fake_etcd(self, fake_etcd, tmp_path):
        # scaled-down canonical test: 2 keys' worth of ops, no partitions
        # (the fake is a single linearizable store)
        opts = {"time-limit": 3, "threads-per-key": 2, "ops-per-key": 30,
                "backend": "cpu"}
        test = etcd.etcd_test(opts)
        test.update({
            "nodes": [fake_etcd] * 2,
            "concurrency": 4,
            "nemesis": None,
            "net": None,
            "db": None,
            "ssh": {"mode": "dummy"},
            "store-dir": str(tmp_path / "run"),
        })
        # drop the nemesis schedule: no nemesis object is installed
        test["generator"] = gen.time_limit(
            3, gen.clients(_inner_workload(opts)))
        out = core.run(test)
        res = out["results"]
        assert res["valid"] is True, res
        assert res["indep"]["valid"] is True
        ops = [o for o in out["history"] if o.is_ok]
        assert len(ops) > 20

    def test_structure(self):
        test = etcd.etcd_test({"time-limit": 1})
        assert test["name"] == "etcd"
        assert test["model"] is not None
        from jepsen_tpu.nemesis import Partitioner
        assert isinstance(test["nemesis"], Partitioner)


def _inner_workload(opts):
    import itertools
    from jepsen_tpu.suites import workloads as wl
    return independent.concurrent_generator(
        opts.get("threads-per-key", 2), itertools.count(),
        lambda k: gen.limit(opts.get("ops-per-key", 30),
                            gen.stagger(1 / 100, wl.register_gen())))
