"""Independent (keyed) generators and lifted checker — mirrors reference
independent_test.clj plus the TPU batched fan-out."""

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu import independent as ind
from jepsen_tpu.checker import Checker
from jepsen_tpu.history import History, Op

from test_generator import pump, ops_of


def vals(result):
    return [o.value for ops in result.values() for o in ops]


def seq_of_values(values):
    return gen.seq([gen.once({"f": "x", "value": v}) for v in values])


class TestKV:
    def test_kv_is_not_a_tuple(self):
        kv = ind.tuple_("k", (0, 1))
        assert ind.is_tuple(kv)
        assert not ind.is_tuple((0, 1))
        k, v = kv
        assert k == "k" and v == (0, 1)

    def test_equality(self):
        assert ind.KV(1, 2) == ind.KV(1, 2)
        assert ind.KV(1, 2) != ind.KV(1, 3)
        assert hash(ind.KV(1, 2)) == hash(ind.KV(1, 2))


class TestSequentialGenerator:
    def test_empty_keys(self):
        out = pump(ind.sequential_generator([], lambda k: {"f": "x"}),
                   concurrency=2)
        assert ops_of(out) == []

    def test_one_key(self):
        g = ind.sequential_generator(
            ["k1"], lambda k: seq_of_values(["ashley", "katchadourian"]))
        out = vals(pump(g, concurrency=1))
        assert out == [ind.KV("k1", "ashley"), ind.KV("k1", "katchadourian")]

    def test_n_keys_in_order(self):
        g = ind.sequential_generator(
            [1, 2, 3], lambda k: seq_of_values(list(range(k))))
        out = vals(pump(g, concurrency=1))
        assert out == [ind.KV(1, 0),
                       ind.KV(2, 0), ind.KV(2, 1),
                       ind.KV(3, 0), ind.KV(3, 1), ind.KV(3, 2)]

    def test_concurrency_stress(self):
        # reference: 1000 keys x 10 values pulled by 10 threads; every kv
        # appears exactly once
        kmax, vmax = 1000, 10
        g = ind.sequential_generator(
            range(kmax), lambda k: seq_of_values(list(range(vmax))))
        out = vals(pump(g, concurrency=10, max_ops=100_000))
        assert len(out) == kmax * vmax
        assert {(kv.key, kv.value) for kv in out} == {
            (k, v) for k in range(kmax) for v in range(vmax)}


class TestConcurrentGenerator:
    def test_empty_keys(self):
        out = pump(ind.concurrent_generator(1, [], lambda k: {"f": "x"}),
                   concurrency=10)
        assert ops_of(out) == []

    def test_too_few_threads(self):
        test = {"concurrency": 10, "nodes": ["n1"]}
        g = ind.concurrent_generator(12, [1], lambda k: {"f": "x"})
        with gen.threads_bound(frozenset(range(10))):
            with pytest.raises(AssertionError, match="raise concurrency"):
                g.op(test, 0)

    def test_uneven_threads(self):
        test = {"concurrency": 11, "nodes": ["n1"]}
        g = ind.concurrent_generator(2, [1], lambda k: {"f": "x"})
        with gen.threads_bound(frozenset(range(11))):
            with pytest.raises(AssertionError, match="multiple of 2"):
                g.op(test, 0)

    def test_fully_concurrent(self):
        # reference: 10 keys x 5 values, 5 threads/key, 100 worker threads
        kmax, vmax, n, threads = 10, 5, 5, 100
        g = ind.concurrent_generator(
            n, range(kmax), lambda k: seq_of_values(list(range(vmax))))
        out = vals(pump(g, concurrency=threads, max_ops=100_000))
        assert {(kv.key, kv.value) for kv in out} == {
            (k, v) for k in range(kmax) for v in range(vmax)}

    def test_group_thread_scoping(self):
        # each key's ops must come only from its group's threads
        seen = {}
        g = ind.concurrent_generator(
            2, range(3), lambda k: seq_of_values(list(range(20))))
        out = pump(g, concurrency=6, max_ops=100_000)
        for thread, ops in out.items():
            for o in ops:
                seen.setdefault(o.value.key, set()).add(thread)
        for k, ts in seen.items():
            groups = {t // 2 for t in ts}
            assert len(groups) == 1, (k, ts)


class TestSubhistory:
    def H(self):
        return History.of([
            Op(type="invoke", f="w", value=ind.KV("a", 1), process=0, time=0),
            Op(type="info", f="kill", value=None, process="nemesis", time=1),
            Op(type="ok", f="w", value=ind.KV("a", 1), process=0, time=2),
            Op(type="invoke", f="w", value=ind.KV("b", 2), process=1, time=3),
            Op(type="ok", f="w", value=ind.KV("b", 2), process=1, time=4),
        ])

    def test_history_keys(self):
        assert ind.history_keys(self.H()) == {"a", "b"}

    def test_subhistory_unwraps_and_keeps_unkeyed(self):
        sub = ind.subhistory("a", self.H())
        assert [o.value for o in sub] == [1, None, 1]
        assert sub[1].process == "nemesis"


class _EvenChecker(Checker):
    def check(self, test, history, opts=None):
        return {"valid": len(history) % 2 == 0}


class TestLiftedChecker:
    def test_reference_even_checker_case(self):
        # independent_test.clj checker-test: keys 1,2,3 with k ops each plus
        # one unsharded op present in every subhistory
        rows = [Op(type="invoke", f="x", value="not-sharded",
                   process=0, time=0)]
        for k in (0, 1, 2, 3):
            for v in range(k):
                rows.append(Op(type="invoke", f="x", value=ind.KV(k, v),
                               process=0, time=len(rows)))
        history = History.of(rows)
        out = ind.checker(_EvenChecker()).check(
            {"name": "independent-checker-test"}, history)
        assert out["valid"] is False
        assert out["results"][1]["valid"] is True
        assert out["results"][2]["valid"] is False
        assert out["results"][3]["valid"] is True
        assert out["failures"] == [2]

    def test_unknown_keys_are_not_failures(self):
        # UNKNOWN is truthy in the reference (independent.clj:287-293):
        # capacity-limited keys must not be misreported as failures.
        class _Tri(Checker):
            def check(self, test, history, opts=None):
                n = len(history)
                return {"valid": (True if n == 1 else
                                  False if n == 2 else "unknown")}

        rows = []
        for k in (1, 2, 3):
            for v in range(k):
                rows.append(Op(type="invoke", f="x", value=ind.KV(k, v),
                               process=0, time=len(rows)))
        out = ind.checker(_Tri()).check(
            {"name": "independent-unknown-test"}, History.of(rows))
        assert out["results"][3]["valid"] == "unknown"
        assert out["failures"] == [2]

    def test_tpu_batched_linearizable(self, tmp_path):
        import random

        from jepsen_tpu.checker.wgl import linearizable
        from jepsen_tpu.models import CASRegister
        from test_linearizable import random_register_history
        from jepsen_tpu.checker.wgl import check_model

        rng = random.Random(3)
        rows = []
        keyed = {}
        t = 0
        for k in range(4):
            h = random_register_history(rng, n_procs=3, n_ops=8)
            keyed[k] = h
            for o in h:
                rows.append(o.replace(value=ind.KV(k, o.value), time=t))
                t += 1
        history = History.of(rows)
        # NOTE: interleaving keys' events sequentially preserves per-key
        # real-time order, so per-key validity matches the original history
        lifted = ind.checker(linearizable(CASRegister(), backend="tpu"))
        test = {"model": CASRegister(), "store-dir": str(tmp_path)}
        out = lifted.check(test, history)
        for k, h in keyed.items():
            want = check_model(h, CASRegister())["valid"]
            assert out["results"][k]["valid"] is want, (k, want)
        # artifacts written per key
        for k in keyed:
            assert (tmp_path / "independent" / str(k)
                    / "results.json").exists()
