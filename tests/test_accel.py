"""Accelerator-init watchdog: a wedged plugin must degrade to CPU with a
warning, never hang the library (cli analyze --backend tpu,
LinearizableChecker(backend='tpu'), check_keyed_tpu all gate on it)."""

import warnings

import pytest

from jepsen_tpu import accel
from jepsen_tpu.models import CASRegister
from jepsen_tpu.testing import simulate_register_history

#: A probe child whose jax.devices hangs — the real wedge, in miniature.
HANGING_PROBE = ("import time\n"
                 "import jax\n"
                 "jax.devices = lambda *a: time.sleep(300)\n"
                 "jax.devices()\n"
                 "print('JEPSEN_ACCEL never')\n")

QUICK_PROBE = "print('JEPSEN_ACCEL faketpu')\n"


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch):
    accel._reset_for_tests()
    # the test process runs with JAX_PLATFORMS=cpu and an initialized
    # backend (conftest); simulate a pristine process with an ambient
    # accelerator plugin
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.delenv("JEPSEN_ACCEL_OK", raising=False)
    monkeypatch.setattr(accel, "_initialized_platform", lambda: None)
    monkeypatch.setattr(accel, "_configured_platforms", lambda: "axon")
    yield
    accel._reset_for_tests()


def test_hanging_probe_degrades_to_cpu(monkeypatch):
    monkeypatch.setattr(accel, "_PROBE_CODE", HANGING_PROBE)
    with pytest.warns(RuntimeWarning, match="degrading to the CPU"):
        plat = accel.ensure_usable("test", timeout=1.5)
    assert plat == "cpu"
    # verdict cached: second call is instant and silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert accel.ensure_usable("test", timeout=1.5) == "cpu"


def test_checker_still_returns_verdict_on_wedge(monkeypatch):
    monkeypatch.setattr(accel, "_PROBE_CODE", HANGING_PROBE)
    monkeypatch.setattr(accel, "PROBE_TIMEOUT_S", 1.5)
    from jepsen_tpu.checker.wgl import LinearizableChecker
    h = simulate_register_history(120, n_procs=3, n_vals=4, seed=2)
    with pytest.warns(RuntimeWarning, match="degrading to the CPU"):
        r = LinearizableChecker(CASRegister(), backend="tpu").check({}, h)
    assert r["valid"] is True


def test_healthy_probe_passes_through(monkeypatch):
    monkeypatch.setattr(accel, "_PROBE_CODE", QUICK_PROBE)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert accel.ensure_usable("test", timeout=30) == "faketpu"


def test_initialized_backend_skips_probe(monkeypatch):
    monkeypatch.setattr(accel, "_initialized_platform", lambda: "cpu")

    def boom(timeout):
        raise AssertionError("probe must not spawn")

    monkeypatch.setattr(accel, "_spawn_probe", boom)
    assert accel.probe_default_backend() == "cpu"


def test_cpu_config_skips_probe(monkeypatch):
    # config, not env, is authoritative: the ambient plugin's startup hook
    # pins jax.config.jax_platforms, and init follows the config
    monkeypatch.setattr(accel, "_configured_platforms", lambda: "cpu")

    def boom(timeout):
        raise AssertionError("probe must not spawn")

    monkeypatch.setattr(accel, "_spawn_probe", boom)
    assert accel.probe_default_backend() == "cpu"


def test_probe_timeout_env_honored_at_call_time(monkeypatch):
    # JEPSEN_ACCEL_PROBE_TIMEOUT is read per call, not at import: an
    # orchestrator that sets it after jepsen_tpu imports still bounds the
    # probe. With a hanging probe child and a ~1s env cap, ensure_usable
    # must degrade in about that long instead of the 300s default.
    import time

    monkeypatch.setattr(accel, "_PROBE_CODE", HANGING_PROBE)
    monkeypatch.setenv("JEPSEN_ACCEL_PROBE_TIMEOUT", "1.0")
    t0 = time.time()
    with pytest.warns(RuntimeWarning, match="degrading to the CPU"):
        plat = accel.ensure_usable("test")  # no explicit timeout arg
    assert plat == "cpu"
    assert time.time() - t0 < 30.0


def test_probe_timeout_env_malformed_falls_back(monkeypatch):
    monkeypatch.setenv("JEPSEN_ACCEL_PROBE_TIMEOUT", "soon")
    monkeypatch.setattr(accel, "PROBE_TIMEOUT_S", 123.0)
    assert accel._probe_timeout() == 123.0


def test_trusted_env_ensure_usable_no_probe_no_warning(monkeypatch):
    # the JEPSEN_ACCEL_OK=1 pre-seed path through ensure_usable: no probe
    # child is spawned, no degradation warning fires, and the caller gets
    # the configured platform back
    monkeypatch.setenv("JEPSEN_ACCEL_OK", "1")

    def boom(timeout):
        raise AssertionError("probe must not spawn")

    monkeypatch.setattr(accel, "_spawn_probe", boom)
    monkeypatch.setattr(accel, "_configured_platforms", lambda: "axon,cpu")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert accel.ensure_usable("test") == "axon"
        # cached: a second call is equally silent
        assert accel.ensure_usable("test") == "axon"


def test_runtime_wedge_is_sticky_and_warns_once():
    assert not accel.runtime_wedged()
    with pytest.warns(RuntimeWarning, match="execution wedged"):
        assert accel.note_runtime_wedge("test", 2.5, level=7)
    assert accel.runtime_wedged()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert not accel.note_runtime_wedge("test", 2.5)  # silent repeat
    # the init verdict is untouched by a run-time wedge
    assert "platform" not in accel._state


def test_trusted_env_skips_probe(monkeypatch):
    monkeypatch.setenv("JEPSEN_ACCEL_OK", "1")

    def boom(timeout):
        raise AssertionError("probe must not spawn")

    monkeypatch.setattr(accel, "_spawn_probe", boom)
    # the trusted path reports a real platform name callers can compare
    # against (never a sentinel string): here the configured list's
    # head, pinned independently of the production parsing
    monkeypatch.setattr(accel, "_initialized_platform", lambda: None)
    monkeypatch.setattr(accel, "_configured_platforms",
                        lambda: "axon,cpu")
    assert accel.probe_default_backend() == "axon"
