"""Consul / zookeeper / raftis / disque suite tests: real wire clients
against in-process fakes (HTTP consul, RESP redis/disque), DB lifecycles
against the dummy control plane."""

import base64
import json
import socketserver
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu import control
from jepsen_tpu.history import Op
from jepsen_tpu.suites import consul, disque, raftis, zookeeper
from jepsen_tpu.suites.resp import RespClient, RespError

from test_nemesis import dummy_test, logs


def op(f, v, p=0):
    return Op(type="invoke", f=f, value=v, process=p, time=0)


# ---------------------------------------------------------------------------
# Fake consul (HTTP KV with index CAS)
# ---------------------------------------------------------------------------


class FakeConsulHandler(BaseHTTPRequestHandler):
    store = {}
    index = [1]
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def _key(self):
        return urllib.parse.urlparse(self.path).path[len("/v1/kv/"):]

    def _reply(self, code, body: bytes):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        k = self._key()
        with self.lock:
            if k not in self.store:
                return self._reply(404, b"")
            val, idx = self.store[k]
            row = [{"Key": k, "ModifyIndex": idx,
                    "Value": base64.b64encode(val).decode()}]
            return self._reply(200, json.dumps(row).encode())

    def do_PUT(self):  # noqa: N802
        k = self._key()
        q = dict(urllib.parse.parse_qsl(
            urllib.parse.urlparse(self.path).query))
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        with self.lock:
            if "cas" in q:
                cur = self.store.get(k)
                if cur is None or cur[1] != int(q["cas"]):
                    return self._reply(200, b"false")
            self.index[0] += 1
            self.store[k] = (body, self.index[0])
            return self._reply(200, b"true")


@pytest.fixture()
def fake_consul():
    FakeConsulHandler.store = {}
    FakeConsulHandler.index = [1]
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeConsulHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"127.0.0.1:{server.server_port}"
    server.shutdown()


class TestConsulClient:
    def test_read_write_cas(self, fake_consul):
        c = consul.ConsulClient().open({}, fake_consul)
        c.setup({"nodes": [fake_consul]})
        got = c.invoke({}, op("read", None))
        assert got.type == "ok" and got.value is None
        assert c.invoke({}, op("write", 3)).type == "ok"
        assert c.invoke({}, op("read", None)).value == 3
        assert c.invoke({}, op("cas", (3, 5))).type == "ok"
        assert c.invoke({}, op("cas", (3, 9))).type == "fail"
        assert c.invoke({}, op("read", None)).value == 5

    def test_down_node(self):
        c = consul.ConsulClient(timeout=0.3).open({}, "127.0.0.1:1")
        assert c.invoke({}, op("read", None)).type == "fail"
        assert c.invoke({}, op("write", 1)).type == "info"


# ---------------------------------------------------------------------------
# Fake RESP server (redis + disque verbs)
# ---------------------------------------------------------------------------


class FakeRespHandler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        while True:
            try:
                line = self.rfile.readline()
            except OSError:
                return
            if not line:
                return
            assert line.startswith(b"*")
            n = int(line[1:].strip())
            args = []
            for _ in range(n):
                ln = self.rfile.readline()
                assert ln.startswith(b"$")
                size = int(ln[1:].strip())
                args.append(self.rfile.read(size))
                self.rfile.read(2)
            self.wfile.write(srv.dispatch([a.decode("utf-8", "replace")
                                           if i != srv.payload_index(args)
                                           else a
                                           for i, a in enumerate(args)]))
            self.wfile.flush()


class FakeRespServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), FakeRespHandler)
        self.kv = {}
        self.jobs = {}     # id -> payload bytes
        self.queue = []    # job ids
        self.next_id = 0
        self.lock = threading.Lock()
        self.watching = {}

    @staticmethod
    def payload_index(args):
        # which arg is a binary payload (disque ADDJOB body)
        if args and args[0].upper() in (b"ADDJOB",):
            return 2
        return -1

    @staticmethod
    def _bulk(b):
        if b is None:
            return b"$-1\r\n"
        if isinstance(b, str):
            b = b.encode()
        return b"$%d\r\n%s\r\n" % (len(b), b)

    def dispatch(self, args) -> bytes:
        cmd = args[0].upper()
        with self.lock:
            if cmd == "GET":
                return self._bulk(self.kv.get(args[1]))
            if cmd == "SET":
                self.kv[args[1]] = args[2]
                return b"+OK\r\n"
            if cmd in ("WATCH", "UNWATCH", "MULTI"):
                return b"+OK\r\n"
            if cmd == "EXEC":
                return b"*1\r\n+OK\r\n"
            if cmd == "ADDJOB":
                self.next_id += 1
                jid = f"D-{self.next_id}"
                self.jobs[jid] = args[2]
                self.queue.append(jid)
                return self._bulk(jid)
            if cmd == "GETJOB":
                if not self.queue:
                    return b"*-1\r\n"
                jid = self.queue.pop(0)
                q = self._bulk("jepsen")
                return (b"*1\r\n*3\r\n" + q + self._bulk(jid)
                        + self._bulk(self.jobs[jid]))
            if cmd == "ACKJOB":
                return b":1\r\n"
            return b"-ERR unknown command\r\n"


@pytest.fixture()
def fake_resp():
    server = FakeRespServer()
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"127.0.0.1:{server.server_address[1]}"
    server.shutdown()


class TestRespClient:
    def test_roundtrip_types(self, fake_resp):
        host, port = fake_resp.rsplit(":", 1)
        c = RespClient(host, int(port))
        assert c.execute("SET", "k", 5) == "OK"
        assert c.execute("GET", "k") == b"5"
        assert c.execute("GET", "nope") is None
        with pytest.raises(RespError):
            c.execute("BOGUS")
        outs = c.execute_many([("SET", "a", 1), ("GET", "a")])
        assert outs == ["OK", b"1"]
        c.close()


class TestRaftisClient:
    def test_register_ops(self, fake_resp):
        c = raftis.RaftisClient().open({}, fake_resp)
        assert c.invoke({}, op("read", None)).value is None
        assert c.invoke({}, op("write", 2)).type == "ok"
        got = c.invoke({}, op("read", None))
        assert got.type == "ok" and got.value == 2
        assert c.invoke({}, op("cas", (2, 7))).type == "ok"
        assert c.invoke({}, op("cas", (3, 9))).type == "fail"

    def test_down_node(self):
        c = raftis.RaftisClient(timeout=0.3).open({}, "127.0.0.1:1")
        assert c.invoke({}, op("read", None)).type == "fail"
        assert c.invoke({}, op("write", 1)).type == "info"


class TestDisqueClient:
    def test_enqueue_dequeue(self, fake_resp):
        c = disque.DisqueClient().open({}, fake_resp)
        assert c.invoke({}, op("enqueue", {"a": 1})).type == "ok"
        assert c.invoke({}, op("enqueue", 2)).type == "ok"
        got = c.invoke({}, op("dequeue", None))
        assert got.type == "ok" and got.value == {"a": 1}
        assert c.invoke({}, op("dequeue", None)).value == 2
        assert c.invoke({}, op("dequeue", None)).type == "fail"

    def test_drain_writes_history(self, fake_resp):
        import threading as _t
        from jepsen_tpu.history import History
        c = disque.DisqueClient().open({}, fake_resp)
        for v in (10, 20, 30):
            c.invoke({}, op("enqueue", v))
        hist = History()
        test = {"_history_lock": _t.Lock(), "_active_histories": [hist],
                "start-time": 0}
        out = c.invoke(test, op("drain", None, p=3))
        assert out.type == "ok" and out.value == "exhausted"
        vals = [o.value for o in hist if o.is_ok and o.f == "dequeue"]
        assert vals == [10, 20, 30]
        assert all(o.process == 3 for o in hist)


class TestZookeeperSuite:
    ZK_GET = """Connecting to n1:2181
WATCHER::
4
cZxid = 0x100
dataVersion = 7
numChildren = 0
"""

    def test_client_read_parses_value_and_version(self):
        t = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "get /jepsen": self.ZK_GET}}})
        with control.session_pool(t):
            c = zookeeper.ZKClient().open(t, "n1")
            got = c.invoke(t, op("read", None))
            assert got.type == "ok" and got.value == 4

    def test_client_cas_uses_version(self):
        t = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "get /jepsen": self.ZK_GET}}})
        with control.session_pool(t):
            c = zookeeper.ZKClient().open(t, "n1")
            got = c.invoke(t, op("cas", (4, 9)))
            assert got.type == "ok"
            assert any("set /jepsen 9 7" in cmd for cmd in logs(t)["n1"])
            # wrong expected value fails without setting
            got = c.invoke(t, op("cas", (5, 9)))
            assert got.type == "fail"

    def test_db_setup_writes_configs(self):
        t = dummy_test()
        with control.session_pool(t):
            zookeeper.ZKDB().setup(t, "n2")
            cmds = logs(t)["n2"]
            assert any("echo 1 > /etc/zookeeper/conf/myid" in c
                       for c in cmds)
            assert any("server.0=n1:2888:3888" in c and "zoo.cfg" in c
                       for c in cmds)
            assert any("service zookeeper restart" in c for c in cmds)

    def test_structure(self):
        t = zookeeper.zk_test({"time-limit": 1})
        assert t["name"] == "zookeeper"
        assert t["model"].value == 0


class TestRegistry:
    def test_registry_has_suites(self):
        from jepsen_tpu import suites
        reg = suites.registry()
        for name in ("etcd", "zookeeper", "consul", "disque", "raftis"):
            assert name in reg
            assert callable(reg[name])


class TestGaleraWorkloads:
    def test_set_client_sql(self):
        from jepsen_tpu.suites.galera import SetClient
        from test_nemesis import dummy_test, logs
        from jepsen_tpu import control
        from jepsen_tpu.history import Op
        t = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "SELECT value": "3\n7\n"}}})
        with control.session_pool(t):
            c = SetClient().open(t, "n1")
            o = Op(type="invoke", f="add", value=9, process=0, time=0)
            assert c.invoke(t, o).type == "ok"
            assert any("INSERT INTO sets (value) VALUES (9)" in s
                       for s in logs(t)["n1"])
            rd = c.invoke(t, Op(type="invoke", f="read", value=None,
                                process=0, time=1))
            assert rd.value == [3, 7]

    def test_bank_transfer_gated_on_rowcount(self):
        from jepsen_tpu.suites.galera import BankClient
        from test_nemesis import dummy_test, logs
        from jepsen_tpu import control
        from jepsen_tpu.history import Op
        op = Op(type="invoke", f="transfer",
                value={"from": 0, "to": 1, "amount": 3}, process=0, time=0)
        t = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "ROW_COUNT()": "1\n"}}})
        with control.session_pool(t):
            c = BankClient(2, 10).open(t, "n1")
            assert c.invoke(t, op).type == "ok"
            stmt = next(s for s in logs(t)["n1"] if "BEGIN" in s)
            assert "SERIALIZABLE" in stmt and "balance >= 3" in stmt
        t2 = dummy_test(**{"ssh": {"mode": "dummy", "dummy-responses": {
            "ROW_COUNT()": "0\n"}}})
        with control.session_pool(t2):
            c = BankClient(2, 10).open(t2, "n1")
            assert c.invoke(t2, op).type == "fail"

    def test_registry_builds_maps(self):
        from jepsen_tpu.suites.galera import bank_test, sets_test
        for fn in (bank_test, sets_test):
            m = fn({"time-limit": 1, "nodes": ["n1", "n2", "n3"]})
            assert m["checker"] is not None and m["generator"] is not None


class TestESSets:
    def test_test_map_builds(self):
        from jepsen_tpu.suites.elasticsearch import sets_test
        m = sets_test({"time-limit": 1, "nodes": ["n1"]})
        assert m["name"] == "elasticsearch-set"

    def test_variant_test_maps_build(self):
        from jepsen_tpu.suites import elasticsearch as es
        for ctor, name in [
                (es.set_cas_test, "elasticsearch-set-cas"),
                (es.set_isolate_primaries_test,
                 "elasticsearch-set-isolate-primaries"),
                (es.set_pause_test, "elasticsearch-set-pause"),
                (es.set_crash_test, "elasticsearch-set-crash"),
                (es.set_bridge_test, "elasticsearch-set-bridge")]:
            m = ctor({"time-limit": 1, "nodes": ["n1", "n2", "n3"]})
            assert m["name"] == name
            assert m["generator"] is not None
            assert m["nemesis"] is not None

    def test_mostly_small_nonempty_subset(self):
        from jepsen_tpu.suites.elasticsearch import (
            mostly_small_nonempty_subset)
        xs = [1, 2, 3, 4, 5]
        sizes = [len(mostly_small_nonempty_subset(xs))
                 for _ in range(300)]
        assert all(1 <= s <= 5 for s in sizes)
        # log-decreasing: small subsets dominate (sets.clj docstring's
        # frequency table: ~38% singletons)
        assert sizes.count(1) > sizes.count(5)

    def test_isolate_self_primaries_grudge(self, monkeypatch):
        from jepsen_tpu.suites import elasticsearch as es
        monkeypatch.setattr(es, "self_primaries",
                            lambda nodes: ["n1", "n3"])
        nem = es.isolate_self_primaries_nemesis()
        grudge = nem.grudge_fn(["n1", "n2", "n3", "n4"])
        # each self-primary is fully cut off from everyone else
        assert grudge["n1"] == {"n2", "n3", "n4"}
        assert grudge["n3"] == {"n1", "n2", "n4"}
        # the rest only drop the primaries, not each other
        assert grudge["n2"] == {"n1", "n3"}

    def test_cas_set_client_version_guarded_add(self):
        from jepsen_tpu.history import Op
        from jepsen_tpu.suites.elasticsearch import CASSetClient
        c = CASSetClient("n1")
        calls = []

        def fake_req(path, method="GET", payload=None):
            calls.append((path, method, payload))
            if method == "GET":
                return {"found": True, "_version": 4,
                        "_source": {"values": [1, 2]}}
            return {}
        c._req = fake_req
        o = Op(type="invoke", f="add", value=3, process=0, time=0)
        out = c.invoke({}, o)
        assert out.type == "ok"
        put = [cl for cl in calls if cl[1] == "PUT"]
        assert put and "version=4" in put[0][0]
        assert put[0][2] == {"values": [1, 2, 3]}

    def test_cas_set_client_conflict_fails(self):
        import urllib.error
        from jepsen_tpu.history import Op
        from jepsen_tpu.suites.elasticsearch import CASSetClient
        c = CASSetClient("n1")

        def fake_req(path, method="GET", payload=None):
            if method == "GET":
                return {"found": True, "_version": 4,
                        "_source": {"values": []}}
            raise urllib.error.HTTPError(path, 409, "conflict", {}, None)
        c._req = fake_req
        o = Op(type="invoke", f="add", value=9, process=0, time=0)
        out = c.invoke({}, o)
        assert out.type == "fail" and out.error == "conflict"


class TestCrateWorkloads:
    def _client(self, script):
        """CrateLostUpdatesClient with a scripted _sql."""
        from jepsen_tpu.suites.sql_family import CrateLostUpdatesClient
        c = CrateLostUpdatesClient("n1")
        calls = []

        def fake_sql(stmt, args=()):
            calls.append((stmt, list(args)))
            for pat, resp in script:
                if pat in stmt:
                    return resp.pop(0) if isinstance(resp, list) else resp
            return {}
        c._sql = fake_sql
        return c, calls

    def test_lost_updates_version_guarded_append(self):
        from jepsen_tpu.history import Op
        c, calls = self._client([
            ("SELECT elements", {"rows": [["1,2", 7]]}),
            ("UPDATE jepsen.sets", {"rowcount": 1}),
        ])
        o = Op(type="invoke", f="add", value=3, process=0, time=0)
        assert c.invoke({}, o).type == "ok"
        upd = next(cl for cl in calls if "UPDATE" in cl[0])
        assert upd[1] == ["1,2,3", 0, 7]     # version-checked write-back

    def test_lost_updates_retries_then_fails(self):
        from jepsen_tpu.history import Op
        c, calls = self._client([
            ("SELECT elements", {"rows": [["", 1]]}),
            ("UPDATE jepsen.sets", {"rowcount": 0}),  # conflict forever
        ])
        o = Op(type="invoke", f="add", value=5, process=0, time=0)
        out = c.invoke({}, o)
        assert out.type == "fail" and out.error == "version-conflict"
        assert sum(1 for cl in calls if "UPDATE" in cl[0]) == c.RETRIES

    def test_read_parses_element_list(self):
        from jepsen_tpu.history import Op
        c, _ = self._client([
            ("REFRESH", {}),
            ("SELECT elements", {"rows": [["4,1,9", 3]]}),
        ])
        o = Op(type="invoke", f="read", value=None, process=0, time=0)
        assert c.invoke({}, o).value == [1, 4, 9]


class TestTiDBNemesisMatrix:
    """tidb/nemesis.clj package registry + tidb/core.clj:95-126 matrix."""

    def test_registry_packages_well_formed(self):
        from jepsen_tpu.suites.sql_family import TIDB_NEMESES
        for name, ctor in TIDB_NEMESES.items():
            m = ctor()
            assert {"name", "client", "during", "final",
                    "clocks"} <= set(m), name

    def test_startstop_targets_a_tidb_binary(self):
        from jepsen_tpu.suites.sql_family import (
            TIDB_BINS, tidb_startstop)
        # the binary is chosen at package-construction time
        # (nemesis.clj:126-132); over a few draws every name is legal
        for _ in range(8):
            m = tidb_startstop()
            assert m["name"] == "startstop"

    def test_matrix_expands_workloads_x_products(self):
        from jepsen_tpu.suites.sql_family import tidb_tests
        ts = tidb_tests({"nemeses": ["none", "parts"],
                         "nemeses2": ["none", "startkill"],
                         "workloads": ["tidb", "tidb-sets"]})
        names = [t["name"] for t in ts]
        # product pairs: (none,startkill) (parts,none) (parts,startkill)
        assert len(ts) == 2 * 3
        assert "tidb-bank-parts+startkill" in names
        assert "tidb-sets-startkill" in names
        for t in ts:
            assert t["generator"] is not None
            assert t["nemesis"] is not None

    def test_composed_package_drives_the_generator(self):
        from jepsen_tpu import generator as gen
        from jepsen_tpu.suites.sql_family import (
            TIDB_NEMESES, tidb_sets_test)
        from jepsen_tpu.suites.cockroachdb import compose_nemeses
        merged = compose_nemeses([TIDB_NEMESES["parts"](),
                                  TIDB_NEMESES["startkill"]()])
        t = tidb_sets_test({"nemesis-map": merged, "time-limit": 1})
        # the final phase must emit the composed (name, f)-tagged stops
        from jepsen_tpu.history import NEMESIS
        fs = []
        g = merged["final"]
        for _ in range(10):
            op = g.op(t, NEMESIS)
            if op is None:
                break
            fs.append(op.f)
        assert ("parts", "stop") in fs and ("startkill", "stop") in fs

    def test_double_gen_interleaves(self):
        from jepsen_tpu.suites.sql_family import tidb_nemesis_double_gen
        g = tidb_nemesis_double_gen()
        assert g["during"] is not None and g["final"] is not None

    def test_matrix_none_x_none_is_one_blank_run(self):
        from jepsen_tpu.suites.sql_family import tidb_tests
        ts = tidb_tests({"nemeses": ["none"], "nemeses2": ["none"],
                         "workloads": ["tidb"]})
        assert len(ts) == 1
        assert ts[0]["name"] == "tidb-bank-blank"

    def test_cli_builds_first_matrix_point(self, tmp_path, capsys):
        import pytest as _pytest
        from jepsen_tpu.suites.sql_family import tidb_main
        # --help smoke: opt spec wires workload + nemesis choices
        with _pytest.raises(SystemExit):
            tidb_main(["test", "--help"])
        out = capsys.readouterr().out
        assert "--workload" in out and "--nemesis2" in out

    def test_double_gen_emits_interleaved_schedule(self, monkeypatch):
        # drive the during-generator (sleeps stubbed) and check the
        # interleave: start1, start2, stop1, stop2, then roles swapped
        import jepsen_tpu.generator as gmod
        monkeypatch.setattr(gmod, "_sleep", lambda dt: None)
        from jepsen_tpu.history import NEMESIS
        from jepsen_tpu.suites.sql_family import tidb_nemesis_double_gen
        g = tidb_nemesis_double_gen()["during"]
        fs = []
        for _ in range(200):
            op = g.op({"concurrency": 1, "nodes": ["n1"]}, NEMESIS)
            if op is None:
                continue
            fs.append(op.f)
            if len(fs) >= 8:
                break
        assert fs[:8] == ["start1", "start2", "stop1", "stop2",
                          "start2", "start1", "stop2", "stop1"]


class TestESPrimaries:
    """primaries()/self_primaries() against a fake /_cluster/state."""

    @pytest.fixture()
    def fake_es(self):
        class Handler(BaseHTTPRequestHandler):
            states = {}

            def log_message(self, *a):
                pass

            def do_GET(self):  # noqa: N802
                body = json.dumps(self.states).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        yield Handler, f"127.0.0.1:{server.server_port}"
        server.shutdown()

    def test_primaries_reads_cluster_state(self, fake_es):
        from jepsen_tpu.suites import elasticsearch as es
        handler, addr = fake_es
        handler.states = {
            "master_node": "abc",
            "nodes": {"abc": {"name": addr}},
        }
        got = es.primaries([addr])
        # the node reports ITSELF as primary -> self-primary
        assert got == {addr: addr}
        assert es.self_primaries([addr]) == [addr]

    def test_unreachable_node_reports_none(self):
        from jepsen_tpu.suites import elasticsearch as es
        got = es.primaries(["127.0.0.1:1"], timeout=0.3)
        assert got == {"127.0.0.1:1": None}
        assert es.self_primaries(["127.0.0.1:1"]) == []


class TestMySQLClusterDB:
    """NDB role/node-id topology (mysql_cluster.clj:60-140)."""

    def test_nodes_conf_partitions_id_space(self):
        from jepsen_tpu.suites.sql_family import mysql_cluster_nodes_conf
        t = {"nodes": ["n1", "n2", "n3", "n4", "n5"]}
        conf = mysql_cluster_nodes_conf(t)
        assert conf.count("[ndb_mgmd]") == 5
        assert conf.count("[ndbd]") == 4      # first four are storage
        assert conf.count("[mysqld]") == 5
        assert "NodeId=1" in conf and "NodeId=11" in conf \
            and "NodeId=21" in conf

    def test_setup_starts_roles(self):
        from jepsen_tpu.suites.sql_family import MySQLClusterDB
        t = dummy_test(**{"nodes": ["n1", "n2", "n3", "n4", "n5"],
                          "ssh": {"mode": "dummy", "dummy-responses": {}}})
        with control.session_pool(t):
            db = MySQLClusterDB()
            db.setup(t, "n1")
            cmds = logs(t)["n1"]
            assert any("ndb_mgmd" in c for c in cmds)
            assert any("ndbd" in c and "connectstring" in c
                       for c in cmds)
            assert any("my.cnf" in c and "ndbcluster" in c
                       for c in cmds)
            db.setup(t, "n5")
            # n5 is not among the first four sorted nodes: no ndbd
            assert not any("ndbd --ndb-connectstring" in c
                           for c in logs(t)["n5"])
            assert any("ndbd --ndb-connectstring" in c
                       for c in logs(t)["n1"])  # the probe is real
