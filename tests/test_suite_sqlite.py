"""Tier-3 end-to-end: the sqlite suites against the REAL engine.

Like tests/test_localkv_e2e.py, but the system under test is a real
production storage engine (SQLite via the stdlib module — the same C
library arbitrating WAL/file locks as in any deployment), in the
reference's postgres-rds single-real-instance pattern. These tests run
the complete core.run lifecycle: schema setup, concurrent workers over
real connections, the lock-hammer nemesis, store artifacts, checking.
"""

import json
import os

import pytest

from jepsen_tpu import core
from jepsen_tpu.suites.sqlitedb import (
    sqlite_bank_test,
    sqlite_register_test,
    sqlite_register_toctou_test,
)


@pytest.fixture
def opts(tmp_path):
    return {
        "store-root": str(tmp_path / "store"),
        "sqlite-path": str(tmp_path / "db" / "test.db"),
    }


class TestSqliteRegister:
    def test_linearizable_under_lock_hammer(self, opts):
        test = sqlite_register_test(
            {**opts, "time-limit": 6, "nemesis-period": 1.5})
        out = core.run(test)
        assert out["results"]["valid"] is True
        history = out["history"]
        ops = [o for o in history if o.process != "nemesis"]
        assert len(ops) > 100, "workload should actually run"
        # the lock hammer must be visible: nemesis rows in the history
        # and busy failures among the writers
        nem = [o for o in history if o.process == "nemesis"]
        assert any("lock held" in str(o.value) for o in nem), nem
        locked = [o for o in ops
                  if o.type == "fail" and o.error
                  and "locked" in str(o.error)]
        assert locked, "lock hammer produced no busy failures"

    def test_store_artifacts(self, opts):
        test = sqlite_register_test({**opts, "time-limit": 3})
        out = core.run(test)
        d = out["store-dir"]
        for f in ("history.jsonl", "results.json", "test.json",
                  "latency-quantiles.svg"):
            assert os.path.exists(os.path.join(d, f)), f
        results = json.load(open(os.path.join(d, "results.json")))
        assert results["valid"] is True


class TestSqliteBank:
    def test_totals_hold(self, opts):
        test = sqlite_bank_test(
            {**opts, "time-limit": 6, "nemesis-period": 1.5})
        out = core.run(test)
        assert out["results"]["valid"] is True
        reads = [o for o in out["history"]
                 if o.is_ok and o.f == "read"
                 and o.process != "nemesis"]
        assert reads and all(sum(r.value) == 50 for r in reads)


class TestSqliteToctou:
    def test_lost_update_is_refuted(self, opts):
        test = sqlite_register_toctou_test(opts)
        out = core.run(test)
        assert out["results"]["valid"] is False
        linear = out["results"]["linear"]
        assert linear["valid"] is False
        # both racing cas's succeeded — the app-level atomicity bug
        oks = [o for o in out["history"]
               if o.is_ok and o.f == "cas"]
        assert len(oks) == 2, oks
        # and the counterexample rendered
        assert os.path.exists(
            os.path.join(out["store-dir"], "linear.svg"))
