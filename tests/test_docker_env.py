"""Smoke tests for the docker/ cluster environment (VERDICT r04 #7).

The reference ships docker/docker-compose.yml + up.sh for running suites
against real 5-node clusters (reference docker/README.md). This build
host has no docker daemon, so these tests validate everything that can
be validated statically — compose structure, shell syntax, Dockerfile
references — and run the real `docker compose config` / build only when
a docker binary exists.
"""

import os
import shutil
import subprocess

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCKER = os.path.join(REPO, "docker")


def _compose():
    with open(os.path.join(DOCKER, "docker-compose.yml")) as f:
        return yaml.safe_load(f)


class TestComposeFile:
    def test_parses_and_has_all_services(self):
        cfg = _compose()
        services = cfg["services"]
        for svc in ("control", "node", "n1", "n2", "n3", "n4", "n5"):
            assert svc in services, f"missing service {svc}"

    def test_five_nodes_extend_the_node_template(self):
        services = _compose()["services"]
        for i in range(1, 6):
            n = services[f"n{i}"]
            assert n.get("extends") in ("node", {"service": "node"}), n
            assert n["hostname"] == f"n{i}"

    def test_control_links_every_node(self):
        control = _compose()["services"]["control"]
        assert sorted(control["links"]) == ["n1", "n2", "n3", "n4", "n5"]

    def test_build_contexts_exist_with_dockerfiles(self):
        services = _compose()["services"]
        for svc in ("control", "node"):
            build = services[svc]["build"]
            ctx = build if isinstance(build, str) else build["context"]
            d = os.path.normpath(os.path.join(DOCKER, ctx))
            assert os.path.isdir(d), d
            assert os.path.isfile(os.path.join(d, "Dockerfile")), d

    def test_env_files_are_generated_by_up_sh(self):
        """The env_file entries point into ./secret, which up.sh
        creates; the script must reference every file compose needs."""
        services = _compose()["services"]
        with open(os.path.join(DOCKER, "up.sh")) as f:
            up = f.read()
        for svc in ("control", "node"):
            env = services[svc]["env_file"]
            for e in env if isinstance(env, list) else [env]:
                assert "secret/" in e, e
                assert os.path.basename(e) in up, e


class TestShellScripts:
    @pytest.mark.parametrize("script", [
        "up.sh", "control/init.sh", "node/init.sh"])
    def test_sh_syntax(self, script):
        path = os.path.join(DOCKER, script)
        assert os.path.isfile(path), path
        proc = subprocess.run(["sh", "-n", path], capture_output=True,
                              text=True)
        assert proc.returncode == 0, proc.stderr

    def test_up_sh_copies_framework_into_control_context(self):
        """The control image COPYs its build context; up.sh must stage
        the framework source there first."""
        with open(os.path.join(DOCKER, "up.sh")) as f:
            up = f.read()
        assert "cp -r ../jepsen_tpu" in up
        assert "docker compose up" in up


class TestDockerfiles:
    @pytest.mark.parametrize("ctx", ["control", "node"])
    def test_copy_sources_exist_or_are_staged(self, ctx):
        """Every COPY source must exist in the build context, or be one
        of the paths up.sh stages (control/jepsen_tpu etc.)."""
        staged = {"jepsen_tpu", "tests", "bench.py", "."}
        d = os.path.join(DOCKER, ctx)
        with open(os.path.join(d, "Dockerfile")) as f:
            for line in f:
                line = line.strip()
                if not line.startswith(("COPY ", "ADD ")):
                    continue
                srcs = line.split()[1:-1]
                for s in srcs:
                    if s.startswith("--"):
                        continue
                    if s in staged or s.split("/")[0] in staged:
                        continue
                    assert os.path.exists(os.path.join(d, s)), (
                        f"{ctx}/Dockerfile references missing {s}")

    @pytest.mark.parametrize("ctx,port", [("control", "8080"),
                                          ("node", "22")])
    def test_from_and_expose(self, ctx, port):
        with open(os.path.join(DOCKER, ctx, "Dockerfile")) as f:
            content = f.read()
        assert content.strip().startswith(("# ", "FROM"))
        assert "FROM " in content
        assert f"EXPOSE {port}" in content

    def test_node_image_has_the_os_layer_tools(self):
        """os/debian.py's setup path expects these on a node."""
        with open(os.path.join(DOCKER, "node", "Dockerfile")) as f:
            content = f.read()
        for tool in ("openssh-server", "sudo", "wget", "iptables",
                     "faketime", "iproute2"):
            assert tool in content, tool


needs_docker = pytest.mark.skipif(
    shutil.which("docker") is None,
    reason="no docker binary on this host (zero-egress build image)")


@needs_docker
class TestRealCompose:
    def test_compose_config_validates(self):
        """`docker compose config` fully resolves the file (extends,
        env_file presence, link graph) — the strongest check short of a
        build."""
        env = os.path.join(DOCKER, "secret")
        os.makedirs(env, exist_ok=True)
        for f in ("control.env", "node.env"):
            p = os.path.join(env, f)
            if not os.path.exists(p):
                with open(p, "w") as fh:
                    fh.write("PLACEHOLDER=1\n")
        proc = subprocess.run(["docker", "compose", "config"],
                              cwd=DOCKER, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
