"""The search-plan verifier (jepsen_tpu.checker.plan +
jepsen_tpu.analysis.plan_lint): bucket enumeration is exhaustive and
deterministic, abstract evaluation performs ZERO XLA compiles and zero
device executions (asserted via a backend_compile-counting hook),
footprint math matches the real packed arrays byte for byte, the
mandatory pre-search gate rejects oversized / indivisible / overflowing
plans with the right PLAN-* rule before any jit factory is touched, the
JTPU_PLAN_GATE=0 kill switch restores identical verdicts and leaves
history artifacts untouched, and CPU-only degradation is graceful. All
tier-1 (marker: plan)."""

import contextlib
import io
import json
import os
import types

import numpy as np
import pytest

from jepsen_tpu import cli
from jepsen_tpu.analysis import plan_lint
from jepsen_tpu.analysis.plan_lint import PlanRejectedError
from jepsen_tpu.checker import plan as plan_mod
from jepsen_tpu.checker import tpu as T
from jepsen_tpu.checker.plan import Candidate, PlanDims
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.models.core import kernel_spec_for
from jepsen_tpu.ops.encode import pack_with_init
from jepsen_tpu.testing import simulate_register_history

pytestmark = pytest.mark.plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures", "plan")


def _history(n=120, seed=3, crash_p=0.02):
    return simulate_register_history(n, n_procs=5, n_vals=4, seed=seed,
                                     crash_p=crash_p)


def _rules(report):
    return sorted({i["rule"] for i in report["issues"]})


@pytest.fixture
def no_limit(monkeypatch):
    monkeypatch.delenv("JTPU_PLAN_BYTES_LIMIT", raising=False)


# ---------------------------------------------------------------------------
# Bucket enumeration
# ---------------------------------------------------------------------------

class TestEnumeration:
    def test_exhaustive_and_deterministic(self):
        dims = PlanDims(n_required=150, n_crashed=3, window_needed=5)
        a = plan_mod.enumerate_candidates(dims)
        b = plan_mod.enumerate_candidates(dims)
        assert a == b
        # both executable kinds, every ladder rung, nothing else
        ladder = T._ladder_for(5)
        assert [c.rung for c in a if c.kind == "single"] == list(ladder)
        assert [c.rung for c in a if c.kind == "segment"] == list(ladder)
        assert {c.kind for c in a} == {"single", "segment"}
        # buckets are the real padded widths
        assert all(c.breq == T._bucket(150) for c in a)
        assert all(c.crw == T._crash_width(3) for c in a)

    def test_explicit_rung_collapses_universe(self):
        dims = PlanDims(n_required=150, window_needed=5)
        cands = plan_mod.enumerate_candidates(dims, capacity=256,
                                              window=64, expand=16)
        assert [c.rung for c in cands] == [(256, 64, 16)] * 2

    def test_keyed_dims_enumerate_batch_ladder(self):
        dims = PlanDims(n_required=500, n_crashed=0, window_needed=8,
                        keys=16)
        cands = plan_mod.enumerate_candidates(dims)
        assert {c.kind for c in cands} == {"batch"}
        assert all(c.keys == 16 for c in cands)
        # the adaptive keyed schedule: slim entry rung (hash tie-break)
        # then the dense double-expansion rung
        assert cands[0].tiebreak == "hash"
        assert cands[1].expand >= cands[0].expand * 2

    def test_mesh_axis_adds_sharded_candidate(self):
        dims = PlanDims(n_required=200, window_needed=8)
        cands = plan_mod.enumerate_candidates(dims, mesh_axis=4)
        sh = [c for c in cands if c.kind == "sharded"]
        assert len(sh) == 1 and sh[0].mesh_axis == 4
        # sharded default expand is rounded up to the mesh axis
        assert sh[0].expand % 4 == 0

    def test_crash_overflow_yields_no_candidates(self):
        dims = PlanDims(n_required=100, n_crashed=T.CRASH_MAX + 1,
                        window_needed=4)
        assert plan_mod.enumerate_candidates(dims) == []
        issues = plan_mod.check_dims(dims)
        assert "PLAN-CRASH-WIDTH" in {i.rule for i in issues}


# ---------------------------------------------------------------------------
# Footprint math
# ---------------------------------------------------------------------------

class TestFootprint:
    @pytest.mark.parametrize("n,crash_p", [(80, 0.0), (150, 0.05),
                                           (400, 0.02)])
    def test_cols_bytes_match_real_packed_history(self, n, crash_p):
        p, kernel = pack_with_init(_history(n, crash_p=crash_p),
                                   CASRegister())
        breq = T._bucket(p.n_required)
        crw = T._crash_width(p.n - p.n_required)
        cols = T._split_packed(p, breq, crw, kernel)
        assert plan_mod.cols_nbytes(breq, crw) == T._cols_nbytes(cols)

    def test_carry_bytes_match_carry0_host(self):
        for cap, win, crw in ((32, 32, 0), (128, 64, 8), (1024, 128, 96)):
            carry = T._carry0_host(cap, win, crw, np.int32(0), 10)
            real = sum(int(np.asarray(x).nbytes) for x in carry)
            assert plan_mod.carry_nbytes(cap, win, crw) == real

    def test_footprint_monotone_in_capacity(self):
        dims_args = dict(kind="segment", window=32, expand=8, unroll=1,
                         breq=1024, crw=16)
        sizes = [plan_mod.footprint(Candidate(capacity=c, **dims_args)
                                    )["total-bytes"]
                 for c in (64, 256, 1024, 4096)]
        assert sizes == sorted(sizes) and sizes[0] < sizes[-1]

    def test_sharded_per_device_share(self):
        c = Candidate(kind="sharded", capacity=4096, window=32,
                      expand=512, unroll=1, breq=1024, crw=0,
                      mesh_axis=8)
        fp = plan_mod.footprint(c)
        assert fp["per-device-bytes"] < fp["total-bytes"]


# ---------------------------------------------------------------------------
# Arithmetic checks
# ---------------------------------------------------------------------------

class TestChecks:
    def test_oom_fires_against_byte_limit(self, no_limit):
        dims = PlanDims(n_required=150, n_crashed=3, window_needed=5)
        rep = plan_mod.analyze(dims, bytes_limit=10_000)
        assert rep["selected"] is None
        assert _rules(rep) == ["PLAN-OOM"]

    def test_cheapest_valid_plan_wins_between_limits(self, no_limit):
        dims = PlanDims(n_required=150, n_crashed=3, window_needed=5)
        # a budget that admits the small rungs but rejects the big ones
        rep = plan_mod.analyze(dims, bytes_limit=1_000_000)
        assert rep["selected"] is not None
        statuses = {c["label"]: c["status"] for c in rep["candidates"]}
        assert statuses[rep["selected"]] == "ok"
        assert "rejected" in statuses.values()
        # the selected plan is the FIRST ok candidate (cheapest rung)
        first_ok = next(c["label"] for c in rep["candidates"]
                        if c["status"] == "ok")
        assert rep["selected"] == first_ok

    def test_shard_indivisible_and_skew(self):
        dims = PlanDims(n_required=200, window_needed=8)
        rep = plan_mod.analyze(dims, mesh_axis=3, capacity=128,
                               expand=10, kinds=("sharded",))
        assert "PLAN-SHARD-INDIVISIBLE" in _rules(rep)
        rep2 = plan_mod.analyze(dims, mesh_axis=8, capacity=256,
                                expand=8, kinds=("sharded",))
        assert "PLAN-SHARD-SKEW" in _rules(rep2)
        assert rep2["selected"] is not None  # a warning does not reject

    def test_int32_overflow_dims(self):
        rep = plan_mod.analyze(PlanDims(n_required=2 ** 30,
                                        window_needed=4))
        assert "PLAN-INT32-OVERFLOW" in _rules(rep)
        assert rep["selected"] is None

    def test_window_rules(self):
        dims = PlanDims(n_required=100, window_needed=4)
        rep = plan_mod.analyze(dims, capacity=64, window=256, expand=8)
        assert "PLAN-WINDOW" in _rules(rep)
        wide = plan_mod.analyze(PlanDims(n_required=100,
                                         window_needed=300))
        assert "PLAN-WINDOW-UNBOUNDED" in _rules(wide)
        # unbounded window is a warning: witness-hunt rungs still run
        assert wide["selected"] is not None

    def test_cpu_degrades_gracefully(self, no_limit):
        # no memory stats on CPU: no byte budget, PLAN-OOM cannot fire
        assert plan_mod.plan_bytes_limit() is None
        dims = PlanDims(n_required=150, n_crashed=3, window_needed=5)
        rep = plan_mod.analyze(dims)
        assert rep["bytes-limit"] is None
        assert "PLAN-OOM" not in _rules(rep)
        assert rep["selected"] is not None


# ---------------------------------------------------------------------------
# Abstract evaluation: zero compiles, zero executions
# ---------------------------------------------------------------------------

class TestZeroCompile:
    def test_trace_performs_no_compile_and_no_execution(self,
                                                        monkeypatch):
        import jax
        import jax._src.compiler as jcompiler
        compiles = []
        real = jcompiler.backend_compile

        def spy(*a, **k):
            compiles.append(1)
            return real(*a, **k)

        monkeypatch.setattr(jcompiler, "backend_compile", spy)
        # explicit .compile() after lower() must also be impossible
        monkeypatch.setattr(
            jax.stages.Lowered, "compile",
            lambda self, *a, **k: (_ for _ in ()).throw(
                AssertionError("plan analysis called Lowered.compile")))
        plan_mod._TRACE_MEMO.clear()
        dims = PlanDims(n_required=100, n_crashed=4, window_needed=6)
        kernel = kernel_spec_for(CASRegister())
        rep = plan_mod.analyze(dims, kernel=kernel, trace=True,
                               cost=True)
        assert compiles == []
        assert rep["selected"] is not None
        traced = [c for c in rep["candidates"] if "traced" in c]
        assert traced and all(c["traced"] for c in traced)
        # the lower()-only cost analysis priced the buckets
        assert any(c.get("cost", {}).get("flops", 0) > 0
                   for c in rep["candidates"])

    def test_trace_memoized_per_bucket(self):
        plan_mod._TRACE_MEMO.clear()
        dims = PlanDims(n_required=64, window_needed=4)
        kernel = kernel_spec_for(CASRegister())
        plan_mod.analyze(dims, kernel=kernel, trace=True)
        n1 = len(plan_mod._TRACE_MEMO)
        plan_mod.analyze(dims, kernel=kernel, trace=True)
        assert len(plan_mod._TRACE_MEMO) == n1

    def test_broken_kernel_bucket_is_a_trace_finding(self):
        # a kernel whose step does not broadcast state over the op grid
        # (the shape bug the matrix caught in the real noop kernel)
        from jepsen_tpu.models.core import KernelSpec
        broken = KernelSpec(name="broken", init_state=0,
                            step=lambda s, f, v1, v2: (s, f == f),
                            f_codes={})
        dims = PlanDims(n_required=64, window_needed=4)
        rep = plan_mod.analyze(dims, kernel=broken, trace=True)
        assert "PLAN-TRACE" in _rules(rep)
        assert rep["selected"] is None


# ---------------------------------------------------------------------------
# The pre-search gate
# ---------------------------------------------------------------------------

class TestGate:
    def _forbid_jit(self, monkeypatch):
        fired = []

        def bomb(name):
            def f(*a, **k):
                fired.append(name)
                raise AssertionError(f"{name} invoked")
            return f

        monkeypatch.setattr(T, "_jit_single", bomb("_jit_single"))
        monkeypatch.setattr(T, "_jit_segment", bomb("_jit_segment"))
        monkeypatch.setattr(T, "_jit_batch", bomb("_jit_batch"))
        return fired

    def test_oversized_capacity_rejected_before_jit(self, monkeypatch):
        monkeypatch.setenv("JTPU_PLAN_BYTES_LIMIT", "200000")
        fired = self._forbid_jit(monkeypatch)
        with pytest.raises(PlanRejectedError) as ei:
            T.check_history_tpu(_history(), CASRegister(),
                                capacity=16384, window=32)
        assert "PLAN-OOM" in str(ei.value)
        assert fired == []
        assert any(f.rule == "PLAN-OOM" for f in ei.value.findings)

    def test_monolithic_path_gated_too(self, monkeypatch):
        monkeypatch.setenv("JTPU_PLAN_BYTES_LIMIT", "200000")
        fired = self._forbid_jit(monkeypatch)
        with pytest.raises(PlanRejectedError):
            T.check_history_tpu(_history(), CASRegister(),
                                capacity=16384, window=32,
                                segment_iters=0)
        assert fired == []

    def test_indivisible_mesh_rejected_before_jit(self, monkeypatch):
        fired = self._forbid_jit(monkeypatch)
        mesh = types.SimpleNamespace(shape={T.POOL_AXIS: 3})
        with pytest.raises(PlanRejectedError) as ei:
            T.check_history_sharded(_history(), CASRegister(), mesh,
                                    capacity=128, expand=10)
        assert "PLAN-SHARD-INDIVISIBLE" in str(ei.value)
        assert fired == []

    def test_int32_overflow_rejected_before_jit(self, monkeypatch):
        fired = self._forbid_jit(monkeypatch)
        packed, kernel = pack_with_init(_history(), CASRegister())
        dims = PlanDims(n_required=2 ** 30, window_needed=4)
        with pytest.raises(PlanRejectedError) as ei:
            plan_mod.gate_ladder(dims, kernel, ((64, 32, 8),),
                                 kind="single", explicit=True)
        assert "PLAN-INT32-OVERFLOW" in str(ei.value)
        assert fired == []

    def test_gate_filters_to_cheapest_valid_rung(self, monkeypatch,
                                                 no_limit):
        monkeypatch.setenv("JTPU_PLAN_BYTES_LIMIT", "1000000")
        r = T.check_history_tpu(_history(), CASRegister(),
                                segment_iters=0)
        assert r["valid"] is True
        plan = r["plan"]
        assert plan["selected"].startswith("single ")
        assert plan["rejected"]  # the big rungs could not fit 1 MB
        assert all("PLAN-OOM" in c["rules"] for c in plan["rejected"])

    def test_supervised_seeds_pool_from_footprint(self, monkeypatch):
        monkeypatch.setenv("JTPU_PLAN_BYTES_LIMIT", "18000")
        r = T.check_history_tpu(_history(), CASRegister())
        assert r["valid"] is True
        seeds = [a for a in r["attempts"]
                 if str(a.get("outcome", "")).startswith(
                     "plan-seeded-pool-")]
        assert seeds and seeds[0]["predicted-bytes"] <= 18000
        assert r["rung"][0] < T._capacity_ladder()[0][0] or \
            r["rung"][0] < 32

    def test_kill_switch_restores_identical_verdicts(self, monkeypatch,
                                                     no_limit):
        h = _history()
        r_on = T.check_history_tpu(h, CASRegister())
        monkeypatch.setenv("JTPU_PLAN_GATE", "0")
        r_off = T.check_history_tpu(h, CASRegister())
        assert "plan" in r_on and "plan" not in r_off

        def stable(r):
            # everything search-semantic; host-measured wall clocks
            # ("device-s", cost entries) legitimately vary run to run
            r = dict(r)
            r.pop("plan", None)
            r.pop("device-s", None)
            r.pop("cost", None)
            return r

        assert stable(r_on) == stable(r_off)

    def test_gate_leaves_history_artifact_byte_identical(
            self, monkeypatch, tmp_path, no_limit):
        # the gate runs in the CHECKER; the recorded history artifact
        # must not change in any way between gate-on and gate-off
        src = os.path.join(REPO, "tests", "fixtures", "lint",
                           "good_history.jsonl")
        art = tmp_path / "history.jsonl"
        art.write_bytes(open(src, "rb").read())
        before = art.read_bytes()
        h = History.from_jsonl(art.read_text())
        v_on = T.check_history_tpu(h, CASRegister())["valid"]
        assert art.read_bytes() == before
        monkeypatch.setenv("JTPU_PLAN_GATE", "0")
        v_off = T.check_history_tpu(h, CASRegister())["valid"]
        assert v_on is True and v_off is True
        assert art.read_bytes() == before

    def test_keyed_gate_attaches_plan_entry(self, no_limit):
        keyed = {k: _history(60, seed=k, crash_p=0.0) for k in range(3)}
        r = T.check_keyed_tpu(keyed, CASRegister())
        assert r["valid"] is True
        assert r["plan"]["selected"].startswith("batch ")

    def test_sharded_gate_passes_divisible_mesh(self, no_limit):
        import jax
        from jax.sharding import Mesh
        devs = np.array(jax.devices()[:2])
        mesh = Mesh(devs, (T.POOL_AXIS,))
        r = T.check_history_sharded(_history(80, crash_p=0.0),
                                    CASRegister(), mesh,
                                    capacity=64, expand=8)
        assert r["valid"] is True
        assert r["plan"]["selected"].startswith("sharded ")


# ---------------------------------------------------------------------------
# The plan lint pass + fixture matrix
# ---------------------------------------------------------------------------

class TestMatrix:
    def test_pinned_matrix_is_clean_arithmetically(self, no_limit):
        fs = plan_lint.lint_matrix()
        assert [f for f in fs if f.severity == "error"] == []

    def test_pinned_matrix_traces_clean_in_budget(self, no_limit):
        import time
        plan_mod._TRACE_MEMO.clear()
        t0 = time.time()
        fs = plan_lint.lint_matrix(trace=True)
        wall = time.time() - t0
        assert [f for f in fs if f.severity == "error"] == []
        assert wall < 30, f"full bucket-universe trace took {wall:.1f}s"

    def test_matrix_runs_inside_repo_lint(self, no_limit):
        from jepsen_tpu import analysis
        fs = analysis.lint_repo(passes=("plan",))
        assert [f for f in fs if f.severity == "error"] == []

    def test_findings_from_report_rules_and_anchors(self):
        rep = plan_mod.analyze(PlanDims(n_required=150, n_crashed=3,
                                        window_needed=5),
                               bytes_limit=10_000)
        fs = plan_lint.findings_from_report(rep)
        assert fs and all(f.rule == "PLAN-OOM" for f in fs)
        assert all(f.anchor.endswith("/PLAN-OOM") for f in fs)


# ---------------------------------------------------------------------------
# CLI + SARIF
# ---------------------------------------------------------------------------

def _run_cli(args):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(cli.default_commands(), args)
    return rc, buf.getvalue()


class TestCLI:
    def test_good_dims_fixture_passes(self, no_limit):
        rc, out = _run_cli(["plan", "--dims",
                            "@" + os.path.join(FIX, "dims_good.json"),
                            "--no-trace"])
        assert rc == cli.OK
        assert "# plan: selected" in out and "REJ" not in out

    def test_oom_fixture_rejected(self, no_limit):
        rc, out = _run_cli(["plan", "--dims",
                            "@" + os.path.join(FIX, "dims_oom.json"),
                            "--no-trace", "--format", "json"])
        assert rc == cli.TEST_FAILED
        rep = json.loads(out)
        assert "PLAN-OOM" in {i["rule"] for i in rep["issues"]}
        assert rep["selected"] is None

    def test_mesh_fixture_rejected(self, no_limit):
        rc, out = _run_cli(
            ["plan", "--dims",
             "@" + os.path.join(FIX, "dims_mesh_indivisible.json"),
             "--no-trace"])
        assert rc == cli.TEST_FAILED
        assert "PLAN-SHARD-INDIVISIBLE" in out

    def test_int32_fixture_rejected(self, no_limit):
        rc, out = _run_cli(
            ["plan", "--dims",
             "@" + os.path.join(FIX, "dims_int32_overflow.json"),
             "--no-trace"])
        assert rc == cli.TEST_FAILED
        assert "PLAN-INT32-OVERFLOW" in out

    def test_cli_traced_run_zero_compiles(self, monkeypatch, no_limit):
        import jax._src.compiler as jcompiler
        compiles = []
        real = jcompiler.backend_compile
        monkeypatch.setattr(
            jcompiler, "backend_compile",
            lambda *a, **k: compiles.append(1) or real(*a, **k))
        plan_mod._TRACE_MEMO.clear()
        rc, out = _run_cli(["plan", "--dims", "100,2,6"])
        assert rc == cli.OK and compiles == []
        assert "MFLOP/level" in out

    def test_history_input(self, no_limit):
        src = os.path.join(REPO, "tests", "fixtures", "lint",
                           "good_history.jsonl")
        rc, out = _run_cli(["plan", "--history", src, "--no-trace"])
        assert rc == cli.OK and "# plan: selected" in out

    def test_sarif_output_is_valid(self, no_limit):
        rc, out = _run_cli(
            ["plan", "--dims",
             "@" + os.path.join(FIX, "dims_mesh_indivisible.json"),
             "--no-trace", "--format", "sarif"])
        assert rc == cli.TEST_FAILED
        doc = json.loads(out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert {r["id"] for r in run["tool"]["driver"]["rules"]} == \
            {"PLAN-SHARD-INDIVISIBLE"}
        res = run["results"][0]
        assert res["level"] == "error"
        assert res["partialFingerprints"]["jtpuAnchor/v1"]

    def test_lint_sarif_format(self, no_limit):
        rc, out = _run_cli(["lint", "--format", "sarif"])
        assert rc == cli.OK
        doc = json.loads(out)
        assert doc["version"] == "2.1.0"
        assert doc["runs"][0]["results"] == []

    def test_summary_line_in_analyze_path(self, no_limit):
        line = plan_mod.summary_line(_history(), CASRegister())
        assert line.startswith("# plan:")
        assert "cheapest" in line and "limit n/a" in line
