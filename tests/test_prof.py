"""Device profiling + fleet telemetry (jepsen_tpu.obs.profiler /
.fleet): the JTPU_PROF opt-in and its no-op guarantees, capture-file
parsing and host/device merging, per-rung kernel rollups, compile-cache
accounting and the `# compile:` line, the fleet merge with skewed
clocks, and the watch/web/CLI surfaces. Tier-1 under the ``prof``
marker (doc/observability.md "Device profiling" / "Compile accounting"
/ "Fleet view" are the operator views)."""

import gzip
import json
import os

import pytest

from jepsen_tpu.obs import fleet as fleet_ns
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import profiler
from jepsen_tpu.obs import trace as obs_trace

pytestmark = pytest.mark.prof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _supervised(tmp_store=None, **kw):
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.ops.encode import pack_with_init
    from jepsen_tpu.resilience import supervised_check_packed
    from jepsen_tpu.testing import simulate_register_history
    h = simulate_register_history(150, n_procs=5, n_vals=4, seed=3)
    p, kernel = pack_with_init(h, CASRegister())
    if tmp_store is not None:
        profiler.attach(str(tmp_store))
    try:
        return supervised_check_packed(p, kernel, capacity=64, expand=8,
                                       segment_iters=8, **kw)
    finally:
        profiler.detach()


# ---------------------------------------------------------------------------
# The opt-in and its no-op guarantees
# ---------------------------------------------------------------------------


class TestProfilerOptIn:
    def setup_method(self):
        profiler._reset_for_tests()

    def test_default_off(self, monkeypatch):
        monkeypatch.delenv("JTPU_PROF", raising=False)
        assert profiler.enabled() is False
        monkeypatch.setenv("JTPU_PROF", "1")
        assert profiler.enabled() is True
        # profiling requires the host tracer: JTPU_TRACE=0 wins
        monkeypatch.setenv("JTPU_TRACE", "0")
        assert profiler.enabled() is False

    def test_prof_off_writes_nothing(self, tmp_path, monkeypatch):
        monkeypatch.delenv("JTPU_PROF", raising=False)
        r = _supervised(tmp_store=tmp_path)
        assert r["valid"] is True
        assert sorted(os.listdir(tmp_path)) == []

    def test_unsupported_platform_is_a_silent_noop(self, tmp_path,
                                                   monkeypatch):
        # JTPU_PROF=1 on a platform whose profiler refuses to start:
        # byte-identical artifacts to JTPU_PROF=0 (same artifact set —
        # no profile/ dir, nothing else) and identical verdicts. The
        # JTPU_TRACE=0 tests' degradation contract, one knob over.
        import jax
        monkeypatch.setenv("JTPU_PROF", "1")

        def refuse(*a, **k):
            raise RuntimeError("profiler unsupported on this platform")

        monkeypatch.setattr(jax.profiler, "start_trace", refuse)
        on_dir = tmp_path / "on"
        on_dir.mkdir()
        r1 = _supervised(tmp_store=on_dir)
        monkeypatch.setenv("JTPU_PROF", "0")
        profiler._reset_for_tests()
        off_dir = tmp_path / "off"
        off_dir.mkdir()
        r0 = _supervised(tmp_store=off_dir)
        assert r1["valid"] == r0["valid"]
        assert r1["levels"] == r0["levels"]
        assert sorted(os.listdir(on_dir)) == sorted(os.listdir(off_dir))
        assert not os.path.isdir(profiler.profile_dir(str(on_dir)))
        # the refusal is sticky: later captures no-op without retrying
        monkeypatch.setenv("JTPU_PROF", "1")
        r2 = _supervised(tmp_store=on_dir)
        assert r2["valid"] is True
        assert not os.path.isdir(profiler.profile_dir(str(on_dir)))

    def test_capture_noop_without_run_dir(self, monkeypatch):
        monkeypatch.setenv("JTPU_PROF", "1")
        with profiler.capture() as cap:
            assert cap.dir is None  # nothing armed: nothing captured

    def test_real_capture_on_cpu(self, tmp_path, monkeypatch):
        # the CPU backend's profiler is real: the capture directory
        # appears, the trace file parses, and merged records nest under
        # checker.segment host spans — the acceptance contract, on the
        # capture this host can actually make
        monkeypatch.setenv("JTPU_PROF", "1")
        tr0 = obs_trace.tracer().recorded
        r = _supervised(tmp_store=tmp_path)
        assert r["valid"] is True
        pdir = profiler.profile_dir(str(tmp_path))
        assert os.path.isdir(pdir)
        assert profiler.find_traces(pdir), "capture wrote no trace file"
        dev, stats = profiler.read_profile(str(tmp_path))
        assert stats["files"] >= 1 and stats["errors"] == 0
        assert dev, "no device-lane records extracted"
        host = [s for s in obs_trace.tracer().spans()]
        assert any(s["name"] == profiler.CAPTURE_SPAN for s in host)
        merged = profiler.merge_into_host(host, dev)
        assert merged
        seg_sids = {s["sid"] for s in host
                    if s["name"] == "checker.segment"}
        assert any(m.get("pid") in seg_sids for m in merged), \
            "no device record parented under a checker.segment span"
        assert obs_trace.tracer().recorded > tr0


# ---------------------------------------------------------------------------
# Parsing + merging (synthetic captures: deterministic, platform-free)
# ---------------------------------------------------------------------------


def _write_capture(tmp_path, events, gz=True):
    pdir = os.path.join(str(tmp_path), profiler.PROFILE_DIRNAME,
                        "plugins", "profile", "2026_08_04")
    os.makedirs(pdir, exist_ok=True)
    doc = {"displayTimeUnit": "ns", "traceEvents": events}
    data = json.dumps(doc).encode()
    if gz:
        path = os.path.join(pdir, "host.trace.json.gz")
        with gzip.open(path, "wb") as f:
            f.write(data)
    else:
        path = os.path.join(pdir, "host.trace.json")
        with open(path, "wb") as f:
            f.write(data)
    return path


_TPU_EVENTS = [
    {"ph": "M", "pid": 9, "name": "process_name",
     "args": {"name": "/device:TPU:0"}},
    {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
     "args": {"name": "XLA Ops"}},
    {"ph": "M", "pid": 7, "name": "process_name",
     "args": {"name": "/host:CPU"}},
    {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
     "args": {"name": "python"}},
    # device kernels: an outer executable with two nested fusions
    {"ph": "X", "pid": 9, "tid": 1, "ts": 100.0, "dur": 50.0,
     "name": "jit_seg.1"},
    {"ph": "X", "pid": 9, "tid": 1, "ts": 110.0, "dur": 20.0,
     "name": "fusion.3"},
    {"ph": "X", "pid": 9, "tid": 1, "ts": 135.0, "dur": 10.0,
     "name": "sort.7"},
    # host python frames must NOT extract
    {"ph": "X", "pid": 7, "tid": 2, "ts": 90.0, "dur": 80.0,
     "name": "$api.py:141 jit"},
]


class TestParseMerge:
    def test_parse_extracts_device_lanes_only(self, tmp_path):
        path = _write_capture(tmp_path, _TPU_EVENTS)
        recs, stats = profiler.parse_trace(path)
        assert stats["device"] == 3
        assert [r["name"] for r in recs] == ["jit_seg.1", "fusion.3",
                                             "sort.7"]
        # us -> ns, lane carries device + thread name
        assert recs[0]["ts"] == 100_000 and recs[0]["dur"] == 50_000
        assert recs[0]["lane"] == "/device:TPU:0/XLA Ops"
        assert all(r["track"] == "device" for r in recs)

    def test_parse_tolerates_garbage_and_truncation(self, tmp_path):
        pdir = os.path.join(str(tmp_path), profiler.PROFILE_DIRNAME)
        os.makedirs(pdir)
        bad = os.path.join(pdir, "torn.trace.json.gz")
        with open(bad, "wb") as f:
            f.write(b"\x1f\x8b\x08\x00garbage-not-a-gzip-stream")
        recs, stats = profiler.parse_trace(bad)
        assert recs == [] and "error" in stats
        recs, stats = profiler.read_profile(str(tmp_path))
        assert recs == [] and stats["errors"] == 1
        # absent capture: empty, no exception
        recs, stats = profiler.read_profile(str(tmp_path / "nope"))
        assert recs == [] and stats["files"] == 0

    def test_xla_runtime_threads_stand_in_on_cpu(self, tmp_path):
        events = [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "pid": 7, "tid": 3, "name": "thread_name",
             "args": {"name": "tf_XLATfrtCpuClient/-117"}},
            {"ph": "X", "pid": 7, "tid": 3, "ts": 10.0, "dur": 5.0,
             "name": "broadcast_add_fusion"},
        ]
        path = _write_capture(tmp_path, events, gz=False)
        recs, stats = profiler.parse_trace(path)
        assert stats["device"] == 1
        assert recs[0]["name"] == "broadcast_add_fusion"

    def test_merge_aligns_clock_and_parents(self):
        host = [
            {"name": profiler.CAPTURE_SPAN, "ts": 1_000_000,
             "dur": 300_000, "tid": 5, "sid": 1},
            {"name": "checker.segment", "ts": 1_050_000, "dur": 100_000,
             "tid": 5, "sid": 2, "pid": 1, "rung": [64, 32, 8]},
            {"name": "checker.segment", "ts": 1_200_000, "dur": 80_000,
             "tid": 5, "sid": 3, "pid": 1, "rung": [32, 32, 4]},
        ]
        dev = [
            # startup work before the first segment (compile etc.)
            {"name": "startup", "ts": 500_000, "dur": 10_000,
             "lane": "/device:TPU:0/XLA Ops", "track": "device"},
            {"name": "fusion.1", "ts": 560_000, "dur": 40_000,
             "lane": "/device:TPU:0/XLA Ops", "track": "device"},
            {"name": "fusion.2", "ts": 710_000, "dur": 40_000,
             "lane": "/device:TPU:0/XLA Ops", "track": "device"},
        ]
        merged = profiler.merge_into_host(host, dev)
        # earliest device ts (500_000) maps onto the capture span start
        # (1_000_000): offset +500_000
        assert merged[0]["ts"] == 1_000_000
        assert merged[0]["pid"] == 1          # pre-segment: capture
        assert merged[1]["ts"] == 1_060_000   # inside segment sid=2
        assert merged[1]["pid"] == 2
        assert merged[1]["rung"] == [64, 32, 8]
        assert merged[2]["ts"] == 1_210_000   # inside segment sid=3
        assert merged[2]["pid"] == 3
        assert merged[2]["rung"] == [32, 32, 4]
        assert merged[0]["tid"] >= profiler.DEVICE_TID_BASE
        # chrome export of the merged stream stays structurally valid
        doc = obs_trace.to_chrome(host + merged)
        assert all("name" in e and "ph" in e
                   for e in doc["traceEvents"])

    def test_merge_empty_device_is_empty(self):
        assert profiler.merge_into_host([{"name": "x", "ts": 0,
                                          "dur": 1, "sid": 1}], []) == []


class TestKernelRollup:
    def test_self_time_subtracts_nested(self):
        dev = [
            {"name": "exec", "ts": 0, "dur": 100, "lane": "L",
             "rung": [64, 32, 8]},
            {"name": "fusion", "ts": 10, "dur": 60, "lane": "L",
             "rung": [64, 32, 8]},
            {"name": "sort", "ts": 20, "dur": 30, "lane": "L",
             "rung": [64, 32, 8]},
            # a second rung's copy of the same kernel rolls up apart
            {"name": "fusion", "ts": 200, "dur": 50, "lane": "L",
             "rung": [32, 32, 4]},
        ]
        rows = profiler.kernel_self_times(dev)
        by = {(tuple(r["rung"]), r["name"]): r for r in rows}
        assert by[((64, 32, 8), "exec")]["self-ns"] == 40   # 100-60
        assert by[((64, 32, 8), "fusion")]["self-ns"] == 30  # 60-30
        assert by[((64, 32, 8), "sort")]["self-ns"] == 30
        assert by[((32, 32, 4), "fusion")]["self-ns"] == 50
        # sorted by self time descending; top_kernels truncates
        assert rows[0]["self-ns"] >= rows[-1]["self-ns"]
        assert len(profiler.top_kernels(dev, k=2)) == 2

    def test_separate_lanes_do_not_nest(self):
        dev = [
            {"name": "a", "ts": 0, "dur": 100, "lane": "L1"},
            {"name": "b", "ts": 10, "dur": 50, "lane": "L2"},
        ]
        rows = {r["name"]: r for r in profiler.kernel_self_times(dev)}
        assert rows["a"]["self-ns"] == 100
        assert rows["b"]["self-ns"] == 50


# ---------------------------------------------------------------------------
# Compile-cache accounting
# ---------------------------------------------------------------------------


class TestCompileAccounting:
    def test_cold_then_cache_hit(self):
        from jepsen_tpu.checker import tpu as T
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.testing import simulate_register_history
        h = simulate_register_history(90, n_procs=3, n_vals=4, seed=41)
        before = T.compile_snapshot()
        # capacity 72 is no ladder rung: a fresh executable shape
        r = T.check_history_tpu(h, CASRegister(), capacity=72,
                                expand=8, segment_iters=16)
        assert r["valid"] is True
        d1 = T.compile_delta(before)
        assert d1["cold"] >= 1
        assert d1["compile-s"] > 0
        mid = T.compile_snapshot()
        r = T.check_history_tpu(h, CASRegister(), capacity=72,
                                expand=8, segment_iters=16)
        d2 = T.compile_delta(mid)
        assert d2["cold"] == 0
        assert d2["cache-hits"] >= 1
        assert d2["execute-s"] > 0

    def test_compile_line_format(self):
        from jepsen_tpu.checker import tpu as T
        delta = {"cold": 2, "cache-hits": 5, "persistent-hits": 0,
                 "persistent-misses": 0, "compile-s": 1.5,
                 "execute-s": 0.25, "transfer-bytes": 2_000_000}
        line = T.compile_line(delta, wall_s=2.0)
        assert line.startswith("# compile: cold=2 shape(s) 1.500s")
        assert "cache-hit=5" in line
        assert "execute=0.250s" in line
        assert "transfer=2.0MB" in line
        assert "host=0.250s of 2.000s wall" in line

    def test_persistent_cache_listener_counts_hits(self):
        from jepsen_tpu.checker import tpu as T
        T._ensure_cache_listener()
        try:
            from jax import monitoring
        except ImportError:
            pytest.skip("no jax.monitoring")
        h0 = T._PERSISTENT_HIT.total()
        m0 = T._PERSISTENT_MISS.total()
        monitoring.record_event("/jax/compilation_cache/cache_hits")
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        assert T._PERSISTENT_HIT.total() == h0 + 1
        assert T._PERSISTENT_MISS.total() == m0 + 1

    def test_segment_path_counts_too(self):
        from jepsen_tpu.checker import tpu as T
        before = T.compile_snapshot()
        r = _supervised()   # capacity=64/8, segment_iters=8
        assert r["valid"] is True
        d = T.compile_delta(before)
        # either cold (first run in this process) or cache-hits moved;
        # every segment is one accounted call
        assert d["cold"] + d["cache-hits"] >= r["segments"]


# ---------------------------------------------------------------------------
# Fleet merge
# ---------------------------------------------------------------------------


def _host_dir(tmp_path, name, epoch_ns, imbalance=None, headroom=None,
              state="done", level=500):
    d = tmp_path / name
    d.mkdir()
    recs = [
        {"name": "core.run", "ts": epoch_ns, "dur": 9_000_000,
         "tid": 1, "sid": 1},
        {"name": "checker.device.batch", "ts": epoch_ns + 1_000_000,
         "dur": 2_000_000, "tid": 1, "sid": 2, "pid": 1},
        {"name": "client.invoke", "ts": epoch_ns + 4_000_000,
         "dur": 1_000, "tid": 2, "sid": 3},
    ]
    with open(d / "trace.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    metrics = {
        "jtpu_search_levels_total": {
            "kind": "counter", "help": "levels",
            "series": {"": float(level)}},
    }
    if imbalance is not None:
        metrics["jtpu_shard_imbalance_ratio"] = {
            "kind": "gauge", "help": "imb", "series": {"": imbalance}}
    if headroom is not None:
        metrics["jtpu_device_headroom_ratio"] = {
            "kind": "gauge", "help": "head", "series": {"": headroom}}
    with open(d / "metrics.json", "w") as f:
        json.dump(metrics, f)
    with open(d / "progress.json", "w") as f:
        json.dump({"state": state, "ts": 1.0, "level": level,
                   "level-budget": 1000, "frontier-rows": 8,
                   "segments": 3}, f)
    return str(d)


class TestFleetMerge:
    def test_merge_aligns_skewed_clocks_and_labels_hosts(self,
                                                         tmp_path):
        # two synthetic hosts whose tracer epochs differ by 5 s: after
        # the merge both anchor spans start at the same instant, every
        # record carries its host, and each (host, tid) track is
        # monotonic
        d1 = _host_dir(tmp_path, "host-a", epoch_ns=1_000_000,
                       imbalance=1.4, headroom=0.3)
        d2 = _host_dir(tmp_path, "host-b",
                       epoch_ns=5_000_000_000, imbalance=1.05,
                       headroom=0.6)
        merged = fleet_ns.merge([d1, d2])
        assert merged["hosts"] == ["host-a", "host-b"]
        assert merged["anchor"] == "checker.device.batch"
        anchors = {}
        for r in merged["trace"]:
            assert r["host"] in ("host-a", "host-b")
            if r["name"] == "checker.device.batch":
                anchors[r["host"]] = r["ts"]
        assert anchors["host-a"] == anchors["host-b"]
        # monotonic per (host, tid) track
        last = {}
        for r in merged["trace"]:
            key = (r["host"], r.get("tid"))
            assert r["ts"] >= last.get(key, float("-inf"))
            last[key] = r["ts"]
        # metrics series re-keyed with a host label + fleet aggregates
        lv = merged["metrics"]["jtpu_search_levels_total"]
        assert lv["series"]['{host="host-a"}'] == 500.0
        assert lv["series"]['{host="host-b"}'] == 500.0
        assert lv["fleet"][""] == 1000.0          # counters sum
        imb = merged["metrics"]["jtpu_shard_imbalance_ratio"]
        assert imb["fleet"][""] == 1.4            # gauges max
        # per-host summary rows carry the fleet-view signals
        rows = {s["host"]: s for s in merged["summary"]}
        assert rows["host-a"]["imbalance"] == pytest.approx(1.4)
        assert rows["host-b"]["headroom"] == pytest.approx(0.6)

    def test_merge_tolerates_ragged_hosts(self, tmp_path):
        d1 = _host_dir(tmp_path, "full", epoch_ns=0)
        bare = tmp_path / "bare"
        bare.mkdir()
        (bare / "progress.json").write_text(
            json.dumps({"state": "searching", "ts": 2.0, "level": 10,
                        "level-budget": 100}))
        merged = fleet_ns.merge([d1, str(bare)])
        assert merged["anchor"] is None  # one host has no trace
        rows = {s["host"]: s for s in merged["summary"]}
        assert rows["bare"]["spans"] == 0
        assert rows["bare"]["level"] == 10
        lines = fleet_ns.format_fleet(merged)
        assert any("bare:" in ln for ln in lines)

    def test_fleet_chrome_export_one_process_per_host(self, tmp_path):
        d1 = _host_dir(tmp_path, "h1", epoch_ns=0)
        d2 = _host_dir(tmp_path, "h2", epoch_ns=7_000_000_000)
        doc = fleet_ns.to_chrome(fleet_ns.merge([d1, d2]))
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert {m["args"]["name"] for m in metas} == \
            {"jtpu:h1", "jtpu:h2"}
        pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] != "M"}
        assert pids == {1, 2}

    def test_watch_fleet_cli(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d1 = _host_dir(tmp_path, "host-a", epoch_ns=0, imbalance=1.2,
                       headroom=0.4)
        d2 = _host_dir(tmp_path, "host-b", epoch_ns=3_000_000_000,
                       headroom=0.1)
        rc = cli.run(cli.default_commands(),
                     ["watch", "--fleet", d1, d2, "--once"])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "host-a:" in out and "host-b:" in out
        assert "imbalance 1.20x" in out
        assert "headroom 10%" in out
        # a missing host dir is an argument error, not a crash
        rc = cli.run(cli.default_commands(),
                     ["watch", "--fleet", str(tmp_path / "nope"),
                      "--once"])
        assert rc == cli.INVALID_ARGS

    def test_web_fleet_endpoint(self, tmp_path):
        import urllib.error
        import urllib.request

        from jepsen_tpu import web
        run = tmp_path / "t" / "20260804T000002.000"
        run.mkdir(parents=True)
        _host_dir(run, "host-a", epoch_ns=0, imbalance=1.3,
                  headroom=0.5)
        _host_dir(run, "host-b", epoch_ns=2_000_000_000,
                  imbalance=1.0, headroom=0.2)
        server = web.serve_background(root=str(tmp_path))
        base = f"http://127.0.0.1:{server.server_port}"
        try:
            page = urllib.request.urlopen(
                base + "/fleet/t/20260804T000002.000").read().decode()
            assert "host-a" in page and "host-b" in page
            assert "1.30x" in page and "20%" in page
            with urllib.request.urlopen(
                    base + "/fleet/t/20260804T000002.000?format=json"
                    ) as r:
                doc = json.load(r)
            assert doc["hosts"] == ["host-a", "host-b"]
            assert len(doc["summary"]) == 2
            # a run without host artifacts 404s rather than 500s
            (tmp_path / "t" / "empty").mkdir()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/fleet/t/empty")
            assert ei.value.code == 404
        finally:
            server.shutdown()

    def test_single_host_run_is_a_one_host_fleet(self, tmp_path):
        d = _host_dir(tmp_path, "solo", epoch_ns=0)
        assert fleet_ns.discover_hosts(d) == [d]
        merged = fleet_ns.merge(fleet_ns.discover_hosts(d))
        assert merged["hosts"] == ["solo"]


# ---------------------------------------------------------------------------
# CLI surfaces: trace summary --format json + kernel lines
# ---------------------------------------------------------------------------


class TestTraceCLISurfaces:
    def _store(self, tmp_path, with_profile=False):
        d = tmp_path / "run"
        d.mkdir()
        tr = obs_trace.Tracer(path=str(d / "trace.jsonl"))
        with tr.span(profiler.CAPTURE_SPAN):
            with tr.span("checker.segment", phase="execute",
                         rung=[64, 32, 8]):
                pass
        tr.detach()
        if with_profile:
            _write_capture(d, _TPU_EVENTS)
        return str(d)

    def test_summary_format_json(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store(tmp_path, with_profile=True)
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", d,
                      "--format", "json"])
        assert rc == cli.OK
        out = capsys.readouterr().out
        doc = json.loads(out)
        assert doc["stats"]["spans"] == 2
        assert "checker.segment" in doc["summary"]
        assert "self-time" in doc
        assert doc["kernels"], "device kernels missing from JSON"
        assert {"name", "self-ns", "count"} <= set(doc["kernels"][0])

    def test_summary_prints_kernel_table(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store(tmp_path, with_profile=True)
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", d])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "device kernels" in out
        assert "fusion.3" in out

    def test_export_merges_device_track(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store(tmp_path, with_profile=True)
        out_path = str(tmp_path / "chrome.json")
        rc = cli.run(cli.default_commands(),
                     ["trace", "export", "--store", d, "-o", out_path])
        assert rc == cli.OK
        with open(out_path) as f:
            doc = json.load(f)
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"checker.segment", "jit_seg.1", "fusion.3"} <= names
        # the device events ride a synthetic device tid
        dev = [e for e in doc["traceEvents"]
               if e["name"] == "jit_seg.1"]
        assert dev[0]["tid"] >= profiler.DEVICE_TID_BASE

    def test_export_without_profile_unchanged(self, tmp_path, capsys):
        from jepsen_tpu import cli
        d = self._store(tmp_path, with_profile=False)
        rc = cli.run(cli.default_commands(),
                     ["trace", "summary", "--store", d])
        assert rc == cli.OK
        out = capsys.readouterr().out
        assert "device kernels" not in out


# ---------------------------------------------------------------------------
# Satellites: ring-drop counter, HELP escaping, bench-gate attribution
# ---------------------------------------------------------------------------


class TestSatellites:
    def test_ring_overflow_counts_drops(self):
        c = obs_metrics.REGISTRY.counter("jtpu_trace_spans_dropped_total")
        before = c.value()
        tr = obs_trace.Tracer(ring=16)
        for i in range(36):
            with tr.span(f"s{i}"):
                pass
        assert tr.dropped == 20
        assert c.value() - before == 20

    def test_help_text_escaping(self):
        reg = obs_metrics.Registry()
        reg.counter("jtpu_esc_total", "line one\nline two \\ back")
        text = reg.to_prometheus()
        assert ("# HELP jtpu_esc_total line one\\nline two \\\\ back"
                in text)
        assert "\nline two" not in text.replace("\\n", "")

    def test_counter_and_histogram_totals(self):
        reg = obs_metrics.Registry()
        c = reg.counter("jtpu_tot_total")
        c.inc(2, kind="a")
        c.inc(3, kind="b")
        assert c.total() == 5
        assert c.total(kind="a") == 2
        h = reg.histogram("jtpu_tot_seconds", buckets=(1.0,))
        h.observe(0.5, phase="execute", kind="x")
        h.observe(2.0, phase="execute", kind="y")
        h.observe(9.0, phase="compile", kind="x")
        t = h.total(phase="execute")
        assert t["count"] == 2 and t["sum"] == pytest.approx(2.5)

    def test_bench_gate_attribution(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import bench_gate
        base = {"value": 1.0, "cold_s": 10.0, "platform": "cpu",
                "compile_s": 8.0, "execute_s": 1.0, "transfer_mb": 5.0,
                "compile": {"cold_compile_s": 8.0,
                            "warm_execute_s": 1.0}}
        for i in range(1, 4):
            with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as f:
                json.dump({"n": i, "parsed": dict(base)}, f)
        # round 4 regresses: cold_s triples, driven by compile_s
        bad = dict(base, cold_s=45.0, compile_s=40.0,
                   compile={"cold_compile_s": 40.0,
                            "warm_execute_s": 1.0})
        with open(tmp_path / "BENCH_r04.json", "w") as f:
            json.dump({"n": 4, "parsed": bad}, f)
        doc = bench_gate.gate(str(tmp_path))
        assert doc["ok"] is False
        att = doc["attribution"]
        assert att, "regression carries no attribution"
        assert att[0]["axis"] in ("compile_s", "compile.cold_compile_s")
        assert att[0]["ratio"] == pytest.approx(5.0)
        execs = [a for a in att if a["axis"] == "execute_s"]
        assert execs and execs[0]["ratio"] == pytest.approx(1.0)
        # a clean trajectory carries none
        with open(tmp_path / "BENCH_r04.json", "w") as f:
            json.dump({"n": 4, "parsed": dict(base)}, f)
        doc = bench_gate.gate(str(tmp_path))
        assert doc["ok"] is True and "attribution" not in doc
