"""Real-tool tests for control.util's install pipeline — the primitive
every DB suite's ``setup`` runs first (wget → cache → tar/unzip →
collapse → mv). The suite-lifecycle tests exercise it as dummy
transcripts; here the SAME code path runs real wget against a local
HTTP server and real tar/unzip on disk, in the local control mode —
catching flag drift in wget/tar/unzip that a transcript cannot.
(Zero-egress build hosts are fine: the server is 127.0.0.1.)
"""

import io
import os
import shutil
import tarfile
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from jepsen_tpu import control
from jepsen_tpu.control import util as cu

NEEDED = [shutil.which(t) for t in ("wget", "tar", "unzip")]

pytestmark = pytest.mark.skipif(
    not all(NEEDED[:2]), reason="no wget/tar binaries")


def _tarball(members):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as t:
        for name, data in members.items():
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            t.addfile(ti, io.BytesIO(data))
    return buf.getvalue()


@pytest.fixture
def served(tmp_path):
    """A local HTTP server holding one tarball (sole top-level dir, the
    collapse case) and one zip (two top-level entries)."""
    payloads = {
        "/db-1.2.3.tar.gz": _tarball({
            "db-1.2.3/bin/dbserver": b"#!/bin/sh\necho serving\n",
            "db-1.2.3/conf/db.conf": b"port=7777\n",
        }),
    }
    zbuf = io.BytesIO()
    with zipfile.ZipFile(zbuf, "w") as z:
        z.writestr("tool.sh", "#!/bin/sh\necho tool\n")
        z.writestr("README", "two top-level entries\n")
    payloads["/tools.zip"] = zbuf.getvalue()
    payloads["/corrupt.tar.gz"] = payloads["/db-1.2.3.tar.gz"][:50]

    hits = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            hits.append(self.path)
            body = payloads.get(self.path)
            if body is None:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}", hits
    srv.shutdown()


@pytest.fixture
def test_map(tmp_path, monkeypatch):
    # redirect the wget cache off the shared /tmp/jepsen
    monkeypatch.setattr(cu, "TMP_DIR_BASE", str(tmp_path / "cache"))
    t = {"nodes": ["localnode"], "ssh": {"mode": "local"}}
    yield t
    for s in t.get("_sessions", {}).values():
        s.close()


class TestInstallArchiveReal:
    def test_tarball_sole_root_collapses(self, served, test_map,
                                         tmp_path):
        base, _ = served
        dest = str(tmp_path / "opt" / "db")
        cu.install_archive(test_map, "localnode",
                           f"{base}/db-1.2.3.tar.gz", dest)
        # the sole top-level dir collapsed into dest itself
        assert open(os.path.join(dest, "conf", "db.conf")).read() \
            == "port=7777\n"
        assert os.path.exists(os.path.join(dest, "bin", "dbserver"))

    def test_cache_hit_skips_refetch(self, served, test_map, tmp_path):
        base, hits = served
        dest = str(tmp_path / "opt" / "db")
        cu.install_archive(test_map, "localnode",
                           f"{base}/db-1.2.3.tar.gz", dest)
        cached = [f for f in os.listdir(cu.TMP_DIR_BASE)
                  if f.endswith(".tar.gz")]
        assert cached, "wget cache is empty"
        fetches = len(hits)
        # a second install must be served from the cache: no new
        # request may reach the server (asserted via the hit counter —
        # a dead URL would instead hang in wget's 20-try backoff if the
        # cache check ever regressed)
        cu.install_archive(test_map, "localnode",
                           f"{base}/db-1.2.3.tar.gz", dest)
        assert len(hits) == fetches, "cache miss: wget refetched"
        assert os.path.exists(os.path.join(dest, "bin", "dbserver"))

    @pytest.mark.skipif(not NEEDED[2], reason="no unzip binary")
    def test_zip_multi_root_keeps_directory(self, served, test_map,
                                            tmp_path):
        base, _ = served
        dest = str(tmp_path / "opt" / "tools")
        cu.install_archive(test_map, "localnode", f"{base}/tools.zip",
                           dest)
        assert sorted(os.listdir(dest)) == ["README", "tool.sh"]

    def test_corrupt_download_retries_then_raises(self, served,
                                                  test_map, tmp_path):
        base, hits = served
        dest = str(tmp_path / "opt" / "bad")
        with pytest.raises(Exception) as ei:
            cu.install_archive(test_map, "localnode",
                               f"{base}/corrupt.tar.gz", dest)
        # the SPECIFIC truncation signature (not just any tar failure:
        # RemoteError always embeds the command line, so matching on
        # 'tar' would be vacuous). GNU gzip prints "unexpected end of
        # file" — which the retry detection must recognize (it used to
        # match only the reference-era "Unexpected EOF").
        assert "unexpected end of file" in str(ei.value).lower() \
            or "unexpected eof" in str(ei.value).lower(), str(ei.value)
        # and the corrupt-download retry actually re-fetched once
        assert hits.count("/corrupt.tar.gz") == 2, hits


class TestWgetReal:
    def test_wget_fetches_and_names_the_file(self, served, test_map,
                                             tmp_path, monkeypatch):
        base, _ = served
        os.makedirs(cu.TMP_DIR_BASE, exist_ok=True)
        with control.cd(cu.TMP_DIR_BASE):
            name = cu.wget(test_map, "localnode",
                           f"{base}/db-1.2.3.tar.gz")
        assert name == "db-1.2.3.tar.gz"
        assert os.path.getsize(
            os.path.join(cu.TMP_DIR_BASE, name)) > 100
