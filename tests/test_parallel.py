"""Mesh/multi-host helpers (jepsen_tpu.parallel) on the virtual 8-device
CPU mesh from conftest."""

import random

from jepsen_tpu import parallel
from jepsen_tpu.models import CASRegister

from test_checker_tpu import random_register_history


class TestMesh:
    def test_make_mesh_all_devices(self):
        mesh = parallel.make_mesh()
        assert dict(mesh.shape) == {"keys": parallel.device_count()}

    def test_make_mesh_subset_and_overflow(self):
        import pytest
        mesh = parallel.make_mesh(4)
        assert dict(mesh.shape) == {"keys": 4}
        with pytest.raises(ValueError):
            parallel.make_mesh(parallel.device_count() + 1)

    def test_shardings(self):
        mesh = parallel.make_mesh(2)
        s = parallel.keyed_sharding(mesh)
        assert s.spec == ("keys",) or tuple(s.spec) == ("keys",)
        r = parallel.replicated_sharding(mesh)
        assert tuple(r.spec) == ()


class TestMultihost:
    def test_initialize_skips_without_coordinator(self, monkeypatch):
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
        assert parallel.initialize_multihost() is False


class TestDistributedCheck:
    def test_keyed_check_over_auto_mesh(self):
        rng = random.Random(5)
        keyed = {k: random_register_history(rng, n_procs=3, n_ops=8,
                                            n_vals=3)
                 for k in range(16)}
        out = parallel.check_keyed_distributed(keyed, CASRegister())
        assert out["backend"] == "tpu"
        assert set(out["results"]) == set(keyed)
        assert out["valid"] in (True, False)

    def test_keyed_mesh_routing_uneven_escalation(self):
        """The dryrun_multichip hardening, under CI: uneven key count
        (padding rows), a non-linearizable key whose False verdict must
        land on exactly that key, and a key only the escalated rung can
        refute — all on the 8-device mesh."""
        import __graft_entry__ as g
        from jepsen_tpu.checker.tpu import check_keyed_tpu
        mesh = parallel.make_mesh(8)
        keyed = {k: random_register_history(random.Random(40 + k),
                                            n_procs=3, n_ops=6, n_vals=3)
                 for k in range(11)}   # 11 + 2 = 13: pads to 16 on 8 devs
        keyed["invalid"] = g._stale_read_history()
        keyed["escalates"] = g._pool_buster_history()
        out = check_keyed_tpu(keyed, CASRegister(), mesh=mesh,
                              ladder=((8, 16, 4), (256, 16, 64)))
        res = out["results"]
        assert res["invalid"]["valid"] is False
        assert res["escalates"]["valid"] is False
        assert out["valid"] is False
        assert len(res) == 13

    def test_pool_buster_unknown_on_slim_rung_alone(self):
        import __graft_entry__ as g
        from jepsen_tpu.checker import UNKNOWN
        from jepsen_tpu.checker.tpu import check_keyed_tpu
        out = check_keyed_tpu({"k": g._pool_buster_history()},
                              CASRegister(), ladder=((8, 16, 4),))
        assert out["results"]["k"]["valid"] is UNKNOWN
        assert out["results"]["k"]["capacity-overflow"] is True


class TestPoolSharded:
    """Single-history scale-out: one search's pool partitioned over the
    mesh (the frontier-parallel WGL of SURVEY §2.5), vs the per-key
    data parallelism of check_keyed_tpu."""

    def _mesh(self):
        from jepsen_tpu.checker.tpu import POOL_AXIS
        return parallel.make_mesh(axis=POOL_AXIS)

    def test_matches_unsharded_verdicts(self):
        from jepsen_tpu.checker import UNKNOWN
        from jepsen_tpu.checker.tpu import (check_history_sharded,
                                            check_history_tpu)
        mesh = self._mesh()
        rng = random.Random(23)
        n = 0
        for i in range(15):
            h = random_register_history(rng, n_procs=4, n_ops=10,
                                        n_vals=3, crash_p=0.1)
            want = check_history_tpu(h, CASRegister())["valid"]
            got = check_history_sharded(h, CASRegister(), mesh,
                                        capacity=64, expand=16)["valid"]
            if UNKNOWN in (want, got):
                continue
            n += 1
            assert got is want, (i, want, got)
        assert n > 8

    def test_refutation_carries_final_states(self):
        from jepsen_tpu.checker.tpu import check_history_sharded
        from jepsen_tpu.history import History, Op
        rows = [Op(type="invoke", f="write", value=1, process=0, time=0),
                Op(type="ok", f="write", value=1, process=0, time=1),
                Op(type="invoke", f="read", value=None, process=1,
                   time=2),
                Op(type="ok", f="read", value=9, process=1, time=3)]
        mesh = self._mesh()
        r = check_history_sharded(History.of(rows), CASRegister(),
                                  mesh, capacity=64, expand=8)
        assert r["valid"] is False
        assert r.get("final-states")
        from jepsen_tpu.checker.tpu import POOL_AXIS
        assert r["pool-sharding"] == f"pool={mesh.shape[POOL_AXIS]}"

    def test_divisibility_enforced(self, monkeypatch):
        import pytest as _pytest
        from jepsen_tpu.analysis.plan_lint import PlanRejectedError
        from jepsen_tpu.checker.tpu import POOL_AXIS, check_history_sharded
        from jepsen_tpu.history import History, Op
        h = History.of([Op(type="invoke", f="write", value=1, process=0,
                           time=0),
                        Op(type="ok", f="write", value=1, process=0,
                           time=1)])
        mesh = self._mesh()
        naxis = mesh.shape[POOL_AXIS]
        if naxis == 1:
            _pytest.skip("1-device mesh: every capacity divides")
        # a capacity the mesh axis provably cannot divide, whatever the
        # ambient device count. The plan gate rejects it with a rule id
        # before any jit work (doc/plan.md)...
        with _pytest.raises(PlanRejectedError,
                            match="PLAN-SHARD-INDIVISIBLE"):
            check_history_sharded(h, CASRegister(), mesh,
                                  capacity=8 * naxis + 1)
        # ...and the legacy ValueError still guards the ungated path.
        monkeypatch.setenv("JTPU_PLAN_GATE", "0")
        with _pytest.raises(ValueError, match="divide"):
            check_history_sharded(h, CASRegister(), mesh,
                                  capacity=8 * naxis + 1)


#: Error-text markers meaning "this host/backend cannot run multi-
#: process computations at all" — a capability gap of the CI image
#: (single-host CPU jaxlibs refuse cross-process programs), not a
#: regression in the DCN seam under test.
_DCN_INCAPABLE_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "multiprocess computations aren't implemented",
    "UNIMPLEMENTED",
    "distributed module is not available",
)


def _skip_if_dcn_incapable(exc: BaseException) -> None:
    """Skip (not fail) when the failure text says the backend cannot do
    multi-process execution — the proper capability guard for the
    two-process DCN test on single-host CPU CI."""
    import pytest
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _DCN_INCAPABLE_MARKERS):
        pytest.skip("multi-process (DCN) computations unsupported on "
                    "this backend/host: " + text.splitlines()[-1][:200])


class TestDCN:
    def test_two_process_dcn_keyed_check(self):
        """Two OS processes join one JAX cluster over a localhost
        coordinator (the DCN seam) and run a keyed check sharded across
        both processes' devices — certifies parallel.py's multi-host
        claim (same jitted program SPMD per host). Skips, rather than
        fails, on hosts whose backend cannot run multi-process
        computations at all (single-host CPU CI images)."""
        import __graft_entry__ as g
        try:
            g.dryrun_dcn(n_procs=2, devices_per_proc=1)
        except RuntimeError as e:
            _skip_if_dcn_incapable(e)
            raise
