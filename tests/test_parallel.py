"""Mesh/multi-host helpers (jepsen_tpu.parallel) on the virtual 8-device
CPU mesh from conftest."""

import random

from jepsen_tpu import parallel
from jepsen_tpu.models import CASRegister

from test_checker_tpu import random_register_history


class TestMesh:
    def test_make_mesh_all_devices(self):
        mesh = parallel.make_mesh()
        assert dict(mesh.shape) == {"keys": parallel.device_count()}

    def test_make_mesh_subset_and_overflow(self):
        import pytest
        mesh = parallel.make_mesh(4)
        assert dict(mesh.shape) == {"keys": 4}
        with pytest.raises(ValueError):
            parallel.make_mesh(parallel.device_count() + 1)

    def test_shardings(self):
        mesh = parallel.make_mesh(2)
        s = parallel.keyed_sharding(mesh)
        assert s.spec == ("keys",) or tuple(s.spec) == ("keys",)
        r = parallel.replicated_sharding(mesh)
        assert tuple(r.spec) == ()


class TestMultihost:
    def test_initialize_skips_without_coordinator(self, monkeypatch):
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
        assert parallel.initialize_multihost() is False


class TestDistributedCheck:
    def test_keyed_check_over_auto_mesh(self):
        rng = random.Random(5)
        keyed = {k: random_register_history(rng, n_procs=3, n_ops=8,
                                            n_vals=3)
                 for k in range(16)}
        out = parallel.check_keyed_distributed(keyed, CASRegister())
        assert out["backend"] == "tpu"
        assert set(out["results"]) == set(keyed)
        assert out["valid"] in (True, False)
