"""Resilient execution layer: checkpointed segments, wedge watchdog, OOM
backoff, structured retries (jepsen_tpu.resilience) — plus the bounded
client ops and nemesis-wedge accounting in core.py.

The injected-fault scenarios carry the ``chaos`` marker;
tools/chaos_matrix.py sweeps the same grid standalone."""

import os
import threading
import time
import warnings

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jepsen_tpu import accel, resilience
from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker.tpu import (
    DEFAULT_SEGMENT_ITERS, _carry0_host, _segment_config, check_history_tpu)
from jepsen_tpu.checker.wgl import check_packed
from jepsen_tpu.models import CASRegister
from jepsen_tpu.models.core import CAS_REGISTER_KERNEL
from jepsen_tpu.ops.encode import pack_with_init
from jepsen_tpu.resilience import (
    FATAL, OOM, TRANSIENT, WEDGE, Checkpoint, RetryPolicy, WedgeError,
    classify_failure, supervised_check_packed)
from jepsen_tpu.testing import simulate_register_history, wide_history


@pytest.fixture(autouse=True)
def clean_resilience_state(monkeypatch):
    """No fault hook or runtime-wedge verdict may leak between tests."""
    monkeypatch.setattr(resilience, "_inject_fault", None)
    # fast, deterministic backoff everywhere
    monkeypatch.setenv("JEPSEN_RETRY_BASE", "0.001")
    yield
    accel._reset_for_tests()


def _packed(h, model=None):
    return pack_with_init(h, model or CASRegister())


def fast_policy(**kw):
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("backoff_cap_s", 0.01)
    return RetryPolicy(**kw)


class TestClassification:
    def test_taxonomy(self):
        assert classify_failure(WedgeError("x")) == WEDGE
        assert classify_failure(MemoryError()) == OOM
        assert classify_failure(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == OOM
        assert classify_failure(
            RuntimeError("XLA:TPU compile... failed to allocate")) == OOM
        assert classify_failure(ConnectionResetError("peer")) == TRANSIENT
        assert classify_failure(TimeoutError("rpc")) == TRANSIENT
        assert classify_failure(
            RuntimeError("UNAVAILABLE: endpoint draining")) == TRANSIENT
        assert classify_failure(ValueError("bad shape")) == FATAL
        assert classify_failure(AssertionError()) == FATAL

    def test_backoff_capped_and_jittered(self):
        p = RetryPolicy(backoff_base_s=0.1, backoff_cap_s=0.5,
                        jitter=False)
        assert p.delay(1) == pytest.approx(0.1)
        assert p.delay(2) == pytest.approx(0.2)
        assert p.delay(10) == pytest.approx(0.5)  # capped
        pj = RetryPolicy(backoff_base_s=0.1, backoff_cap_s=0.5)
        for a in (1, 3, 9):
            d = pj.delay(a)
            full = min(0.5, 0.1 * 2 ** (a - 1))
            assert full / 2 <= d <= full

    def test_policy_env_defaults(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_RETRY_BASE", "0.25")
        monkeypatch.setenv("JEPSEN_RETRY_CAP", "2.5")
        p = RetryPolicy()
        assert p.backoff_base_s == pytest.approx(0.25)
        assert p.backoff_cap_s == pytest.approx(2.5)


class TestSegmentConfig:
    def test_default_and_env(self, monkeypatch):
        monkeypatch.delenv("JTPU_SEGMENT_ITERS", raising=False)
        assert _segment_config(None) == DEFAULT_SEGMENT_ITERS
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "64")
        assert _segment_config(None) == 64
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "0")
        assert _segment_config(None) is None
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "nope")
        with pytest.raises(ValueError):
            _segment_config(None)

    def test_argument_wins(self, monkeypatch):
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "64")
        assert _segment_config(7) == 7
        assert _segment_config(0) is None


class TestSegmentedEqualsMonolithic:
    """The restructured search (host loop of device segments) must be
    bit-identical in verdicts and level counts to the single
    while_loop — the body sequence is the same computation."""

    def test_differential_random_histories(self, monkeypatch):
        import random
        rng = random.Random(11)
        for i in range(8):
            h = simulate_register_history(
                120, n_procs=4, n_vals=4, seed=100 + i,
                crash_p=0.05, overlap_p=0.5)
            monkeypatch.setenv("JTPU_SEGMENT_ITERS", "0")
            mono = check_history_tpu(h, CASRegister())
            monkeypatch.setenv("JTPU_SEGMENT_ITERS",
                               str(rng.choice((3, 9, 17))))
            seg = check_history_tpu(h, CASRegister())
            assert seg["valid"] == mono["valid"]
            assert seg["levels"] == mono["levels"]
            assert seg["rung"] == mono["rung"]
            assert seg["segments"] >= 1

    def test_refutation_evidence_identical(self, monkeypatch):
        h = wide_history(16, 2, seed=5, corrupt=True)
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "0")
        mono = check_history_tpu(h, CASRegister())
        monkeypatch.setenv("JTPU_SEGMENT_ITERS", "6")
        seg = check_history_tpu(h, CASRegister())
        assert mono["valid"] is False and seg["valid"] is False
        for k in ("max-linearized-prefix", "final-states", "levels"):
            assert seg.get(k) == mono.get(k), k

    def test_result_carries_resilience_keys(self):
        h = simulate_register_history(60, n_procs=3, n_vals=4, seed=1)
        r = check_history_tpu(h, CASRegister(), segment_iters=8)
        assert r["valid"] is True
        assert r["segments"] >= 1
        assert r["segment-iters"] == 8
        assert r["attempts"][-1]["event"] == "rung-complete"
        assert r["attempts"][-1]["levels"] == r["levels"]


class TestCheckpointRoundTrip:
    def test_save_load_roundtrip(self, tmp_path):
        h = simulate_register_history(100, n_procs=4, n_vals=4, seed=3,
                                      crash_p=0.05)
        p, kernel = _packed(h)
        cps = []
        base = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                       segment_iters=6,
                                       on_checkpoint=cps.append)
        assert cps, "multi-segment search must emit checkpoints"
        mid = cps[len(cps) // 2]
        path = str(tmp_path / "search.npz")
        mid.save(path)
        loaded = Checkpoint.load(path)
        assert loaded.rung == mid.rung
        assert loaded.segment == mid.segment
        assert loaded.level == mid.level
        for a, b in zip(loaded.carry, mid.carry):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        resumed = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                          segment_iters=6, resume=loaded)
        assert resumed["valid"] == base["valid"]
        assert resumed["levels"] == base["levels"]

    @pytest.mark.chaos
    def test_kill_mid_run_resumes_identically(self):
        """The acceptance scenario: a search killed after N segments
        (injected exception) resumes from its checkpoint and returns a
        verdict identical to the uninterrupted run, attempt trail
        included."""
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=9,
                                      crash_p=0.03)
        p, kernel = _packed(h)
        uninterrupted = supervised_check_packed(
            p, kernel, capacity=128, expand=8, segment_iters=8)
        cps = []

        def kill_at_3(ctx):
            if ctx["segment"] == 3:
                raise ValueError("simulated mid-run kill")

        resilience._inject_fault = kill_at_3
        try:
            with pytest.raises(ValueError) as ei:
                supervised_check_packed(
                    p, kernel, capacity=128, expand=8, segment_iters=8,
                    policy=fast_policy(max_retries=0),
                    on_checkpoint=cps.append)
        finally:
            resilience._inject_fault = None
        # the dying search left its trail on the exception
        assert ei.value.resilience_trail
        assert len(cps) == 3
        resumed = supervised_check_packed(
            p, kernel, capacity=128, expand=8, segment_iters=8,
            resume=cps[-1])
        assert resumed["valid"] == uninterrupted["valid"]
        assert resumed["levels"] == uninterrupted["levels"]
        assert resumed["segments"] == uninterrupted["segments"]


class TestInjectedOOM:
    @pytest.mark.chaos
    def test_oom_shrinks_pool_and_stays_sound(self):
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=4,
                                      crash_p=0.02)
        p, kernel = _packed(h)
        oracle = check_packed(p, kernel)
        fired = []

        def oom_twice(ctx):
            if ctx["segment"] == 1 and len(fired) < 2:
                fired.append(ctx["effective"])
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory while trying to "
                    "allocate the search pool")

        resilience._inject_fault = oom_twice
        try:
            r = supervised_check_packed(
                p, kernel, capacity=256, expand=16, segment_iters=8,
                policy=fast_policy())
        finally:
            resilience._inject_fault = None
        assert len(fired) == 2
        assert r["valid"] == oracle["valid"]
        assert r["rung"][0] == 64          # 256 -> 128 -> 64
        assert r["rung-requested"] == (256, 32, 16)
        ooms = [a for a in r["attempts"] if a.get("event") == OOM]
        assert len(ooms) == 2
        assert all("backoff-s" in a for a in ooms)

    @pytest.mark.chaos
    def test_oom_at_floor_reports_unknown_with_trail(self):
        h = simulate_register_history(80, n_procs=4, n_vals=4, seed=6)
        p, kernel = _packed(h)

        def always_oom(ctx):
            raise MemoryError("oom")

        resilience._inject_fault = always_oom
        try:
            r = supervised_check_packed(
                p, kernel, capacity=32, expand=4, segment_iters=8,
                policy=fast_policy())
        finally:
            resilience._inject_fault = None
        assert r["valid"] is UNKNOWN
        assert "pool floor" in r["error"]
        assert any(a.get("outcome") == "gave-up" for a in r["attempts"])


class TestInjectedWedge:
    @pytest.mark.chaos
    def test_wedge_falls_back_to_cpu_and_completes(self):
        """The acceptance scenario: a mid-execution wedge completes on
        the CPU fallback instead of hanging."""
        h = simulate_register_history(150, n_procs=5, n_vals=4, seed=8,
                                      crash_p=0.02)
        p, kernel = _packed(h)
        base = supervised_check_packed(p, kernel, capacity=128, expand=8,
                                       segment_iters=8)
        wedged = []

        def wedge_once(ctx):
            if ctx["segment"] == 2 and not wedged:
                wedged.append(ctx["backend"])
                raise WedgeError("injected wedged execution")

        resilience._inject_fault = wedge_once
        try:
            with pytest.warns(RuntimeWarning,
                              match="execution wedged.*mid-run"):
                r = supervised_check_packed(
                    p, kernel, capacity=128, expand=8, segment_iters=8)
        finally:
            resilience._inject_fault = None
        assert wedged == ["default"]
        assert r["valid"] == base["valid"]
        assert r["levels"] == base["levels"]
        assert r["backend-fallback"] == "cpu"
        wedge_events = [a for a in r["attempts"]
                        if a.get("event") == WEDGE]
        assert wedge_events and \
            wedge_events[0]["outcome"] == "cpu-fallback"
        # the wedge verdict is process-sticky: later supervised work
        # starts on the fallback directly
        assert accel.runtime_wedged()

    @pytest.mark.chaos
    def test_wedge_on_fallback_gives_up_visibly(self):
        h = simulate_register_history(60, n_procs=3, n_vals=4, seed=2)
        p, kernel = _packed(h)

        def always_wedge(ctx):
            raise WedgeError("wedged everywhere")

        resilience._inject_fault = always_wedge
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                r = supervised_check_packed(
                    p, kernel, capacity=32, expand=4, segment_iters=8)
        finally:
            resilience._inject_fault = None
        assert r["valid"] is UNKNOWN
        assert "wedged" in r["error"]

    def test_real_watchdog_fires_on_hung_segment(self, monkeypatch):
        """A device executable that genuinely blocks past its deadline is
        abandoned by the REAL watchdog thread in _call_segment and
        classified as a wedge; the checkpoint completes on the CPU
        fallback."""
        from jepsen_tpu.checker import tpu as T
        h = simulate_register_history(60, n_procs=3, n_vals=4, seed=2)
        p, kernel = _packed(h)
        real_jit = T._jit_segment
        hung = []
        release = threading.Event()

        def hanging_jit(*a, **kw):
            fn = real_jit(*a, **kw)

            def wrapped(*args):
                if not hung:
                    hung.append(1)
                    release.wait(20)  # wedge the first device call
                    raise RuntimeError("hung call released at teardown")
                return fn(*args)

            return wrapped

        monkeypatch.setattr(T, "_jit_segment", hanging_jit)
        try:
            with pytest.warns(RuntimeWarning, match="execution wedged"):
                r = supervised_check_packed(
                    p, kernel, capacity=32, expand=4, segment_iters=8,
                    deadline_s=0.2)
        finally:
            release.set()  # free the abandoned watchdog thread
        assert hung, "the hang must actually have been exercised"
        assert r["valid"] in (True, False)
        assert r["backend-fallback"] == "cpu"


class TestInjectedTransient:
    @pytest.mark.chaos
    def test_transient_retries_with_jitter_then_succeeds(self):
        h = simulate_register_history(80, n_procs=4, n_vals=4, seed=5)
        p, kernel = _packed(h)
        base = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                       segment_iters=8)
        flakes = []

        def flaky(ctx):
            if ctx["segment"] == 1 and len(flakes) < 2:
                flakes.append(1)
                raise ConnectionResetError("transient RPC reset")

        resilience._inject_fault = flaky
        try:
            r = supervised_check_packed(
                p, kernel, capacity=64, expand=8, segment_iters=8,
                policy=fast_policy(max_retries=3))
        finally:
            resilience._inject_fault = None
        assert r["valid"] == base["valid"]
        assert r["levels"] == base["levels"]
        retries = [a for a in r["attempts"]
                   if a.get("event") == TRANSIENT]
        assert len(retries) == 2

    def test_transient_retries_exhausted_raises_with_trail(self):
        h = simulate_register_history(60, n_procs=3, n_vals=4, seed=5)
        p, kernel = _packed(h)

        def always_flaky(ctx):
            raise TimeoutError("endpoint never answers")

        resilience._inject_fault = always_flaky
        try:
            with pytest.raises(TimeoutError) as ei:
                supervised_check_packed(
                    p, kernel, capacity=32, expand=4, segment_iters=8,
                    policy=fast_policy(max_retries=2))
        finally:
            resilience._inject_fault = None
        trail = ei.value.resilience_trail
        assert [a["outcome"] for a in trail] == \
            ["retry-1", "retry-2", "retries-exhausted"]


class TestLocalKVHistories:
    """Checkpoint/resume on histories produced by the REAL localkv
    harness run (daemons, sockets, SIGSTOP nemesis) — the workload the
    resilient checker exists to serve."""

    @pytest.fixture(scope="class")
    def localkv_history(self):
        from jepsen_tpu import core
        from jepsen_tpu.suites.localkv import localkv_test
        test = localkv_test({"time-limit": 3, "nemesis-period": 1.0})
        test["store-dir"] = None
        test["checker"] = None
        out = core.run(test)
        h = out["history"]
        assert len(h) > 20, "localkv run produced no meaningful history"
        return h

    def test_checkpoint_resume_equals_uninterrupted(self, localkv_history):
        p, kernel = _packed(localkv_history)
        cps = []
        uninterrupted = supervised_check_packed(
            p, kernel, segment_iters=4, on_checkpoint=cps.append)
        oracle = check_packed(p, kernel)
        assert uninterrupted["valid"] == oracle["valid"]
        if not cps:
            pytest.skip("search finished inside one segment")
        for cp in (cps[0], cps[len(cps) // 2]):
            resumed = supervised_check_packed(
                p, kernel, segment_iters=4, resume=cp)
            assert resumed["valid"] == uninterrupted["valid"]
            assert resumed["levels"] == uninterrupted["levels"]

    @pytest.mark.chaos
    def test_kill_and_resume_on_real_history(self, localkv_history):
        p, kernel = _packed(localkv_history)
        base = supervised_check_packed(p, kernel, segment_iters=4)
        if base["segments"] < 3:
            pytest.skip("history too short to kill mid-run")
        cps = []

        def killer(ctx):
            if ctx["segment"] == 2:
                raise ValueError("killed mid-run")

        resilience._inject_fault = killer
        try:
            with pytest.raises(ValueError):
                supervised_check_packed(
                    p, kernel, segment_iters=4,
                    policy=fast_policy(max_retries=0),
                    on_checkpoint=cps.append)
        finally:
            resilience._inject_fault = None
        resumed = supervised_check_packed(p, kernel, segment_iters=4,
                                          resume=cps[-1])
        assert resumed["valid"] == base["valid"]
        assert resumed["levels"] == base["levels"]


class TestBoundedClientOps:
    @pytest.mark.chaos
    def test_hung_client_yields_info_and_reincarnates(self):
        """with_op_timeout end to end: one op hangs forever; the worker
        records :info and reincarnates instead of stalling the run."""
        from jepsen_tpu import core, generator as gen
        from jepsen_tpu.testing import (
            AtomClient, SharedRegister, atom_test)

        class HangingClient(AtomClient):
            invocations = [0]
            hangs = [0]

            def open(self, test, node):
                return HangingClient(self.register)

            def invoke(self, test, op):
                # deterministic early hang: the 3rd invocation overall
                # blocks forever, while plenty of generator budget
                # remains for the reincarnated process to act
                with lock:
                    HangingClient.invocations[0] += 1
                    me = HangingClient.invocations[0]
                if me == 3 and not HangingClient.hangs[0]:
                    HangingClient.hangs[0] = 1
                    threading.Event().wait(60)  # a truly stuck call
                return super().invoke(test, op)

        lock = threading.Lock()

        reg = SharedRegister()
        t = atom_test(reg)
        t["client"] = HangingClient(reg)
        t["op-timeout"] = 0.3
        t["store-dir"] = None
        # staggered so the generator still has ops to hand out after the
        # 0.3s hang detection — the reincarnated process must get to act
        t["generator"] = gen.clients(
            gen.stagger(0.02, gen.limit(150, gen.cas_gen())))
        t0 = time.time()
        t = core.run(t)
        assert time.time() - t0 < 30, "hung op must not stall the run"
        assert HangingClient.hangs[0] == 1
        h = t["history"]
        infos = [o for o in h
                 if o.is_info and o.process != "nemesis"
                 and o.error and "OpTimeout" in str(o.error)]
        assert infos, "the hung op must surface as an info op"
        # reincarnation: the abandoned logical process never acts again,
        # its thread continues as p + concurrency
        dead = infos[0].process
        later = [o for o in h if o.index > infos[0].index]
        assert all(o.process != dead for o in later)
        assert any(isinstance(o.process, int)
                   and o.process >= t["concurrency"] for o in h)

    def test_with_op_timeout_passthrough_and_raise(self):
        from jepsen_tpu.core import OpTimeout, with_op_timeout
        assert with_op_timeout(5.0, lambda: 42) == 42
        with pytest.raises(OpTimeout, match="op-timeout"):
            with_op_timeout(0.05, lambda: time.sleep(10))
        # exceptions pass through unmangled
        with pytest.raises(KeyError):
            with_op_timeout(5.0, lambda: {}["missing"])


class TestNemesisWedgeAccounting:
    @pytest.mark.chaos
    def test_wedged_nemesis_recorded_and_net_healed(self):
        from jepsen_tpu import core, generator as gen
        from jepsen_tpu.history import NEMESIS
        from jepsen_tpu.testing import atom_test

        release = threading.Event()

        class StuckNemesis:
            def setup(self, test):
                return self

            def invoke(self, test, op):
                release.wait(60)  # wedged mid-invocation
                return op

            def teardown(self, test):
                teardowns.append(1)

        class RecordingNet:
            def __init__(self):
                self.healed = 0

            def heal(self, test):
                self.healed += 1

        teardowns = []
        net = RecordingNet()
        t = atom_test()
        t["nemesis"] = StuckNemesis()
        t["net"] = net
        t["store-dir"] = None
        t["nemesis-join-timeout"] = 0.5
        t["generator"] = gen.Any_([
            gen.nemesis(gen.limit(1, gen.start_stop(0, 0))),
            gen.clients(gen.limit(10, gen.cas_gen())),
        ])
        try:
            t = core.run(t)
        finally:
            release.set()
        wedge_ops = [o for o in t["history"]
                     if o.process == NEMESIS and o.f == "nemesis-wedged"]
        assert len(wedge_ops) == 1
        assert "join timeout" in str(wedge_ops[0].error)
        assert teardowns, "teardown must still run for a wedged nemesis"
        assert net.healed >= 1, "net.heal must run in the safety net"

    def test_worker_crash_still_heals_and_tears_down(self):
        from jepsen_tpu import core, generator as gen
        from jepsen_tpu.history import Op
        from jepsen_tpu.testing import atom_test

        class BoomGen(gen.Generator):
            """Hands out a few reads, then blows up the workers."""

            def __init__(self):
                self.n = 0
                self.lock = threading.Lock()

            def op(self, test, process):
                with self.lock:
                    self.n += 1
                    if self.n > 5:
                        raise RuntimeError("generator exploded mid-phase")
                return Op(type="invoke", f="read", value=None)

        class RecordingNet:
            def __init__(self):
                self.healed = 0

            def heal(self, test):
                self.healed += 1

        torn = []

        class Nem:
            def setup(self, test):
                return self

            def invoke(self, test, op):
                return op

            def teardown(self, test):
                torn.append(1)

        net = RecordingNet()
        t = atom_test()
        t["nemesis"] = Nem()
        t["net"] = net
        t["store-dir"] = None
        t["generator"] = gen.clients(BoomGen())
        with pytest.raises(RuntimeError, match="exploded"):
            core.run(t)
        assert torn, "nemesis teardown must run when a worker raises"
        assert net.healed >= 1, "net.heal must run when a worker raises"
