"""Time-series + SLO + usage + flight-recorder tests (the `obs`
marker, doc/observability.md).

Covers the tsdb ring buffers (frame merge, downsampling, wraparound),
the CRC'd segment file's torn-tail resume (the restarted store equals
the pre-kill series prefix), windowed quantiles, the SLO engine's
multi-window burn-rate breach/recovery state machine, per-tenant usage
metering and its WAL reconciliation invariant, the flight recorder's
atomic dumps, and the JTPU_TSDB kill-switch identity contract
(`tsdb_enabled=False` leaves the daemon's metric families, artifacts,
and HTTP surface exactly as PR-18 shipped them).
"""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import serve as serve_ns
from jepsen_tpu.obs import flightrec as flightrec_ns
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import slo as slo_ns
from jepsen_tpu.obs import tsdb as tsdb_ns
from jepsen_tpu.obs import usage as usage_ns

pytestmark = pytest.mark.obs


def _clock(start=1000.0):
    """A settable fake wall clock for driving sample_once()."""
    now = [float(start)]

    def fn():
        return now[0]

    fn.set = lambda t: now.__setitem__(0, float(t))
    fn.advance = lambda d: now.__setitem__(0, now[0] + d)
    return fn


def _db(tmp_path, clock, resolutions=(("1s", 1.0, 8),), persist=False,
        registry=None):
    return tsdb_ns.TSDB(str(tmp_path / "tsdb"), cadence=999.0,
                        now_fn=clock, registry=registry,
                        resolutions=resolutions, persist=persist)


# ---------------------------------------------------------------------------
# Rings: merge, downsample, wraparound
# ---------------------------------------------------------------------------


class TestRings:
    def test_counter_frames_merge_and_downsample(self, tmp_path):
        reg = obs_metrics.Registry()
        c = reg.counter("jobs_total")
        clock = _clock(100.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 32), ("4s", 4.0, 32)))
        for _ in range(8):          # ticks at t=100..107, +2 each
            c.inc(2)
            db.sample_once()
            clock.advance(1.0)
        fine = db.series("jobs_total", "1s")
        assert fine == [[100.0 + i, 2.0] for i in range(8)]
        coarse = db.series("jobs_total", "4s")
        # 100..103 fold into the t0=100 frame, 104..107 into t0=104
        assert coarse == [[100.0, 8.0], [104.0, 8.0]]
        assert db.kind("jobs_total") == "counter"

    def test_ring_wraparound_keeps_newest_frames(self, tmp_path):
        reg = obs_metrics.Registry()
        c = reg.counter("spins_total")
        clock = _clock(0.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 4),))
        for _ in range(10):
            c.inc()
            db.sample_once()
            clock.advance(1.0)
        frames = db.series("spins_total")
        assert len(frames) == 4     # maxlen, not uptime
        assert [fr[0] for fr in frames] == [6.0, 7.0, 8.0, 9.0]

    def test_gauge_is_last_write_wins_within_a_frame(self, tmp_path):
        reg = obs_metrics.Registry()
        g = reg.gauge("depth")
        clock = _clock(50.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("10s", 10.0, 8),))
        for v, t in ((3, 50.0), (9, 51.0), (1, 62.0)):
            g.set(v)
            clock.set(t)
            db.sample_once()
        assert db.series("depth", "10s") == [[50.0, 9.0], [60.0, 1.0]]
        assert db.latest("depth", "10s") == 1.0

    def test_histogram_window_and_quantile(self, tmp_path):
        reg = obs_metrics.Registry()
        h = reg.histogram("lat_s", buckets=(0.1, 1.0))
        clock = _clock(200.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 64),))
        for _ in range(9):
            h.observe(0.05, tenant="a")
        h.observe(0.5, tenant="b")
        db.sample_once()
        cnt, sm, buckets = db.window_hist("lat_s", 10.0)
        assert cnt == 10 and buckets[:2] == [9, 1]
        assert sm == pytest.approx(9 * 0.05 + 0.5)
        assert db.quantile("lat_s", 0.5, 10.0) == 0.1
        assert db.quantile("lat_s", 0.99, 10.0) == 1.0
        # label-superset matching: only tenant=b's series
        assert db.quantile("lat_s", 0.5, 10.0, tenant="b") == 1.0
        assert db.bounds("lat_s") == [0.1, 1.0]
        # an empty window has no quantile
        assert db.quantile("lat_s", 0.5, 10.0,
                           now=clock() + 100.0) is None

    def test_window_delta_sums_matching_series(self, tmp_path):
        reg = obs_metrics.Registry()
        c = reg.counter("reqs_total")
        clock = _clock(0.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 64),))
        c.inc(3, tenant="a")
        c.inc(5, tenant="b")
        db.sample_once()
        assert db.window_delta("reqs_total", 10.0) == 8.0
        assert db.window_delta("reqs_total", 10.0, tenant="a") == 3.0
        assert sorted(db.series_keys("reqs_total")) == \
            ['{tenant="a"}', '{tenant="b"}']

    def test_registry_reset_clamps_the_delta(self, tmp_path):
        reg = obs_metrics.Registry()
        c = reg.counter("boots_total")
        clock = _clock(0.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 64),))
        c.inc(10)
        db.sample_once()
        reg.reset()
        c = reg.counter("boots_total")
        c.inc(2)
        clock.advance(1.0)
        db.sample_once()
        # a reset must not show up as a -8 spike: new value is the delta
        assert db.series("boots_total") == [[0.0, 10.0], [1.0, 2.0]]


# ---------------------------------------------------------------------------
# Segment file: persistence, torn-tail resume, compaction
# ---------------------------------------------------------------------------


class TestResume:
    def _run_ticks(self, tmp_path, n=8):
        reg = obs_metrics.Registry()
        c = reg.counter("work_total")
        clock = _clock(100.0)
        db = tsdb_ns.TSDB(str(tmp_path / "tsdb"), cadence=999.0,
                          now_fn=clock, registry=reg,
                          resolutions=(("1s", 1.0, 32),), persist=True)
        db.start()
        try:
            for _ in range(n):
                c.inc(2)
                db.sample_once()
                clock.advance(1.0)
            return db.series("work_total"), db.path
        finally:
            db.stop()

    def test_resume_rebuilds_the_series(self, tmp_path):
        pre, path = self._run_ticks(tmp_path)
        assert os.path.exists(path)
        db2 = tsdb_ns.TSDB(os.path.dirname(path), cadence=999.0,
                           now_fn=_clock(200.0),
                           registry=obs_metrics.Registry(),
                           resolutions=(("1s", 1.0, 32),), persist=True)
        db2.resume()
        assert db2.series("work_total") == pre
        assert db2.kind("work_total") == "counter"
        assert db2.resumed_records == len(pre)

    def test_torn_tail_resume_equals_prekill_prefix(self, tmp_path):
        """SIGKILL mid-append loses at most the torn final record; the
        resumed series is exactly the pre-kill prefix."""
        pre, path = self._run_ticks(tmp_path)
        with open(path, "rb") as f:
            blob = f.read()
        with open(path, "wb") as f:
            f.write(blob[:-5])      # tear the final record mid-line
        db2 = tsdb_ns.TSDB(os.path.dirname(path), cadence=999.0,
                           now_fn=_clock(200.0),
                           registry=obs_metrics.Registry(),
                           resolutions=(("1s", 1.0, 32),), persist=True)
        db2.resume()
        assert db2.series("work_total") == pre[:-1]

    def test_compaction_bounds_the_file_and_survives_resume(
            self, tmp_path):
        reg = obs_metrics.Registry()
        c = reg.counter("churn_total")
        clock = _clock(0.0)
        db = tsdb_ns.TSDB(str(tmp_path / "tsdb"), cadence=999.0,
                          now_fn=clock, registry=reg,
                          resolutions=(("1s", 1.0, 8),), persist=True)
        db.start()
        try:
            for _ in range(tsdb_ns.COMPACT_RECORDS + 5):
                c.inc()
                db.sample_once()
                clock.advance(1.0)
        finally:
            db.stop()
        records, stats = __import__(
            "jepsen_tpu.journal", fromlist=["journal"]
        ).read_json_records(db.path)
        assert not stats.get("corrupt") and not stats.get("torn")
        assert len(records) < tsdb_ns.COMPACT_RECORDS
        assert records[0]["k"] == "ckpt"
        db2 = tsdb_ns.TSDB(os.path.dirname(db.path), cadence=999.0,
                           now_fn=clock, registry=obs_metrics.Registry(),
                           resolutions=(("1s", 1.0, 8),), persist=True)
        db2.resume()
        assert db2.series("churn_total") == db.series("churn_total")


# ---------------------------------------------------------------------------
# Histogram.quantile + snapshot ts (the metrics satellites)
# ---------------------------------------------------------------------------


class TestMetricsSatellites:
    def test_histogram_quantile_nearest_rank(self):
        reg = obs_metrics.Registry()
        h = reg.histogram("q_s", buckets=(0.1, 1.0))
        for _ in range(9):
            h.observe(0.05)
        h.observe(0.5)
        assert h.quantile(0.5) == 0.1
        assert h.quantile(0.9) == 0.1
        assert h.quantile(0.91) == 1.0
        assert h.quantile(1.0) == 1.0

    def test_histogram_quantile_filters_by_labels(self):
        reg = obs_metrics.Registry()
        h = reg.histogram("ql_s", buckets=(0.1, 1.0))
        h.observe(0.05, tenant="a")
        h.observe(0.5, tenant="b")
        assert h.quantile(0.99, tenant="a") == 0.1
        assert h.quantile(0.99, tenant="b") == 1.0
        assert h.quantile(0.99) == 1.0      # no filter: both series
        assert h.quantile(0.5, tenant="missing") is None

    def test_quantile_overflow_clamps_to_top_bound(self):
        reg = obs_metrics.Registry()
        h = reg.histogram("ovf_s", buckets=(0.1, 1.0))
        h.observe(50.0)
        assert h.quantile(0.99) == 1.0

    def test_snapshot_carries_wall_clock_ts(self):
        before = time.time()
        snap = obs_metrics.REGISTRY.snapshot()
        assert before <= snap["ts"] <= time.time()
        for name, doc in snap.items():
            if name != "ts":
                assert isinstance(doc, dict) and "kind" in doc


# ---------------------------------------------------------------------------
# SLO engine: burn rates, breach, recovery
# ---------------------------------------------------------------------------


class TestSLO:
    def _engine(self, tmp_path):
        reg = obs_metrics.Registry()
        h = reg.histogram("req_s", buckets=(0.1, 1.0))
        clock = _clock(1000.0)
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 120),))
        events = []
        obj = slo_ns.Objective("lat-p90", "latency", target=0.9,
                               metric="req_s", threshold=0.1)
        eng = slo_ns.SLOEngine(db, objectives=[obj],
                               windows=(("2s", 2.0), ("60s", 60.0)),
                               burn_threshold=1.0,
                               on_transition=events.append)
        return reg, h, clock, db, eng, events

    def test_breach_then_recovery(self, tmp_path):
        reg, h, clock, db, eng, events = self._engine(tmp_path)
        # 5 slow requests: bad ratio 1.0, budget 0.1 -> burn 10 in both
        # windows -> breach
        for _ in range(5):
            h.observe(0.5)
        db.sample_once()            # the tick drives eng.evaluate
        snap = eng.snapshot()
        obj = snap["objectives"]["lat-p90"]
        assert obj["breached"] is True
        assert obj["windows"]["2s"] == pytest.approx(10.0)
        assert eng.breached() == 1
        assert eng.max_burn() == pytest.approx(10.0)
        assert [e["event"] for e in events] == ["slo.breach"]
        # 10s later the short window holds only fast requests: it
        # cools below the threshold -> recovery (the long window still
        # burns, by design: recovery needs only the short window)
        clock.advance(10.0)
        for _ in range(20):
            h.observe(0.05)
        db.sample_once()
        obj = eng.snapshot()["objectives"]["lat-p90"]
        assert obj["breached"] is False
        assert eng.breached() == 0
        assert [e["event"] for e in events] == ["slo.breach",
                                               "slo.recovered"]

    def test_no_traffic_burns_nothing(self, tmp_path):
        reg, h, clock, db, eng, events = self._engine(tmp_path)
        snap = eng.evaluate()
        obj = snap["objectives"]["lat-p90"]
        assert obj["breached"] is False
        assert obj["windows"] == {"2s": 0.0, "60s": 0.0}
        assert events == []

    def test_burn_rate_gauge_is_set(self, tmp_path):
        reg, h, clock, db, eng, events = self._engine(tmp_path)
        for _ in range(5):
            h.observe(0.5, tenant="hot")
        db.sample_once()
        g = obs_metrics.REGISTRY.gauge("jtpu_slo_burn_rate")
        assert g.value(slo="lat-p90", tenant="all") == \
            pytest.approx(10.0)
        assert g.value(slo="lat-p90", tenant="hot") == \
            pytest.approx(10.0)

    def test_default_objectives_cover_the_serve_slos(self):
        names = {o.name for o in slo_ns.default_objectives()}
        assert names == {"verdict-latency-p99", "queue-wait-p95",
                         "availability"}


# ---------------------------------------------------------------------------
# Usage metering
# ---------------------------------------------------------------------------


class TestUsage:
    def test_totals_roll_up_and_replay_reconciles(self):
        m = usage_ns.UsageMeter()
        u1 = {"ops": 8, "device-s": 0.25, "bytes": 100,
              "lane-share": 0.5, "seconds": 1.5}
        u2 = {"ops": 4, "device-s": 0.5, "bytes": 50,
              "lane-share": 1.0, "seconds": 0.5}
        m.record("a", u1)
        m.record("a", u1)
        m.record("b", u2)
        doc = m.totals()
        assert doc["tenants"]["a"]["requests"] == 2
        assert doc["tenants"]["a"]["device-s"] == pytest.approx(0.5)
        assert doc["total"]["ops"] == pytest.approx(20)
        assert m.top() == ("a", 0.5)
        # the WAL fold is the same meter over the same docs
        m2 = usage_ns.UsageMeter()
        n = usage_ns.replay(m2, [
            {"event": "done", "tenant": "a", "usage": u1},
            {"event": "done", "tenant": "a", "usage": u1},
            {"event": "done", "tenant": "b", "usage": u2},
            {"event": "submit", "tenant": "a"},
            {"event": "done", "tenant": "old-no-usage"},
        ])
        assert n == 3
        assert m2.totals() == doc

    def test_tenant_filter(self):
        m = usage_ns.UsageMeter()
        m.record("a", {"ops": 1})
        m.record("b", {"ops": 2})
        doc = m.totals(tenant="b")
        assert sorted(doc["tenants"]) == ["b"]
        assert doc["total"]["ops"] == pytest.approx(2)


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_dump_is_whole_listable_and_loadable(self, tmp_path):
        fr = flightrec_ns.FlightRecorder(str(tmp_path), seconds=60.0)
        path = fr.dump("unit-test", extra={"k": "v"})
        assert path and os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)
        assert doc["reason"] == "unit-test"
        assert doc["window-s"] == 60.0
        assert doc["extra"] == {"k": "v"}
        assert "metrics" in doc and "spans" in doc
        dumps = flightrec_ns.list_dumps(str(tmp_path))
        assert len(dumps) == 1 and dumps[0]["reason"] == "unit-test"
        loaded = flightrec_ns.load_dump(str(tmp_path),
                                        dumps[0]["name"])
        assert loaded["reason"] == "unit-test"

    def test_same_reason_dumps_are_rate_limited(self, tmp_path):
        fr = flightrec_ns.FlightRecorder(str(tmp_path), seconds=60.0)
        assert fr.dump("flappy") is not None
        assert fr.dump("flappy") is None            # inside cooldown
        assert fr.dump("other-reason") is not None  # per-reason limit

    def test_load_dump_rejects_path_traversal(self, tmp_path):
        fr = flightrec_ns.FlightRecorder(str(tmp_path), seconds=60.0)
        fr.dump("safe")
        assert flightrec_ns.load_dump(str(tmp_path),
                                      "../secrets.json") is None
        assert flightrec_ns.load_dump(str(tmp_path), "nope.txt") is None

    def test_tsdb_annex_rides_along(self, tmp_path):
        reg = obs_metrics.Registry()
        c = reg.counter("annex_total")
        clock = _clock(time.time())
        db = _db(tmp_path, clock, registry=reg,
                 resolutions=(("1s", 1.0, 64),))
        c.inc(3)
        db.sample_once()
        fr = flightrec_ns.FlightRecorder(str(tmp_path / "rec"),
                                         seconds=60.0, tsdb=db)
        path = fr.dump("with-tsdb")
        with open(path) as f:
            doc = json.load(f)
        assert "annex_total" in doc["tsdb"]["series"]


# ---------------------------------------------------------------------------
# The serve daemon: wiring + the JTPU_TSDB kill-switch identity
# ---------------------------------------------------------------------------


def _ops(n_pairs=2, value=1):
    rows = []
    t = 0
    for i in range(n_pairs):
        rows.append({"type": "invoke", "f": "write", "value": value + i,
                     "process": 0, "time": t})
        rows.append({"type": "ok", "f": "write", "value": value + i,
                     "process": 0, "time": t + 1})
        rows.append({"type": "invoke", "f": "read", "value": None,
                     "process": 1, "time": t + 2})
        rows.append({"type": "ok", "f": "read", "value": value + i,
                     "process": 1, "time": t + 3})
        t += 4
    return rows


def _wait_done(daemon, rid, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = daemon.status(rid)
        if doc and doc["state"] == "done":
            return doc
        time.sleep(0.02)
    raise AssertionError(f"request {rid} never finished: "
                         f"{daemon.status(rid)}")


class TestServeWiring:
    def test_usage_totals_reconcile_with_the_wal(self, tmp_path):
        """The acceptance invariant: live totals == the WAL fold, and a
        restarted daemon replays the meter back to the same totals."""
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu")
        assert cfg.tsdb_on
        d1 = serve_ns.CheckDaemon(cfg)
        d1.start()
        try:
            for tenant in ("a", "b", "a"):
                code, body, _ = d1.submit({"tenant": tenant,
                                           "model": "cas-register",
                                           "history": _ops()})
                assert code == 202
                _wait_done(d1, body["id"])
            live = d1.usage.totals()
        finally:
            d1.stop()
        wal = os.path.join(cfg.root, serve_ns.WAL_NAME)
        assert live == usage_ns.from_wal(wal)
        assert live["tenants"]["a"]["requests"] == 2
        assert live["tenants"]["b"]["requests"] == 1
        assert live["total"]["ops"] == pytest.approx(3 * len(_ops()))
        assert live["total"]["device-s"] > 0
        # the restarted daemon replays the meter from the same WAL
        d2 = serve_ns.CheckDaemon(serve_ns.ServeConfig(
            root=cfg.root, backend="tpu"))
        d2.start()
        try:
            assert d2.usage.totals() == live
            assert d2.tsdb.resumed_records >= 0   # tsdb resumed too
        finally:
            d2.stop()

    def test_request_seconds_series_lands_in_the_tsdb(self, tmp_path):
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu", tsdb_cadence_s=0.1)
        d = serve_ns.CheckDaemon(cfg)
        d.start()
        try:
            code, body, _ = d.submit({"tenant": "t1",
                                      "model": "cas-register",
                                      "history": _ops()})
            assert code == 202
            _wait_done(d, body["id"])
            d.tsdb.sample_once()
            cnt, sm, _b = d.tsdb.window_hist(
                "jtpu_serve_request_seconds", 3600.0, tenant="t1")
            assert cnt >= 1 and sm > 0
            assert d.tsdb.quantile("jtpu_serve_request_seconds", 0.99,
                                   3600.0) is not None
            assert os.path.exists(os.path.join(cfg.root,
                                               tsdb_ns.TSDB_NAME))
            assert "slo" in d.healthz()
        finally:
            d.stop()

    def test_kill_switch_leaves_pr18_surface_identical(self, tmp_path,
                                                       monkeypatch):
        """JTPU_TSDB=0: no new metric families, no new artifacts, no
        new healthz keys, and the new routes 404."""
        monkeypatch.setenv("JTPU_TSDB", "0")
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu", tsdb_enabled=True)
        assert cfg.tsdb_on is False     # env wins over the field
        families_before = {
            ln for ln in obs_metrics.REGISTRY.to_prometheus()
            .splitlines() if ln.startswith("# TYPE ")}
        daemon, server = serve_ns.run_daemon(
            cfg, host="127.0.0.1", port=0)
        port = server.server_port
        try:
            assert daemon.tsdb is None and daemon.slo is None
            assert daemon.usage is None and daemon.flightrec is None
            code, body, _ = daemon.submit({"model": "cas-register",
                                           "history": _ops()})
            assert code == 202
            doc = _wait_done(daemon, body["id"])
            assert doc["result"]["valid"] is True
            assert "slo" not in daemon.healthz()
            families_after = {
                ln for ln in obs_metrics.REGISTRY.to_prometheus()
                .splitlines() if ln.startswith("# TYPE ")}
            assert families_after == families_before
            for path in ("/usage", "/slo", "/flightrec"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=10)
                assert ei.value.code == 404
        finally:
            server.shutdown()
            daemon.stop()
        assert not os.path.exists(os.path.join(cfg.root,
                                               tsdb_ns.TSDB_NAME))
        assert not os.path.exists(os.path.join(cfg.root,
                                               flightrec_ns.DIR_NAME))
        # the WAL done records carry no usage field either
        from jepsen_tpu import journal
        records, _ = journal.read_json_records(
            os.path.join(cfg.root, serve_ns.WAL_NAME))
        assert all("usage" not in r for r in records
                   if r.get("event") == "done")

    def test_breaker_trip_dumps_the_flight_recorder(self, tmp_path,
                                                    monkeypatch):
        cfg = serve_ns.ServeConfig(root=str(tmp_path / "serve"),
                                   backend="tpu", breaker_fails=1)
        d = serve_ns.CheckDaemon(cfg)
        monkeypatch.setattr(
            serve_ns.CheckDaemon, "_check",
            lambda self, req: {"valid": "unknown",
                               "error": "RESOURCE_EXHAUSTED (fake)",
                               "error-class": "oom"})
        d.start()
        try:
            code, body, _ = d.submit({"tenant": "boom",
                                      "model": "cas-register",
                                      "history": _ops()})
            assert code == 202
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if flightrec_ns.list_dumps(cfg.root):
                    break
                time.sleep(0.05)
        finally:
            d.stop()
        dumps = flightrec_ns.list_dumps(cfg.root)
        reasons = {dmp["reason"] for dmp in dumps}
        assert "breaker-trip" in reasons
        trip = next(dmp for dmp in dumps
                    if dmp["reason"] == "breaker-trip")
        doc = flightrec_ns.load_dump(cfg.root, trip["name"])
        assert doc["extra"]["class"]
        assert doc["extra"]["bucket"]
