"""Crash-safe histories: the write-ahead op journal, run recovery, and
post-fault convergence probes.

The WAL (jepsen_tpu/journal.py) tees every op ``core.conj_op`` records
into ``history.wal``; ``store.recover_run`` + the ``recover`` CLI
subcommand rebuild a checkable history from whatever a killed run left
on disk. tools/chaos_matrix.py drives the real SIGKILL-a-localkv-run
variant standalone; here the same machinery is exercised on synthetic
dead runs, torn tails, corrupt records, and sync-policy knobs."""

import io
import contextlib
import json
import os
import subprocess
import sys

import pytest

from jepsen_tpu import cli, core, journal, store
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis
from jepsen_tpu.history import History, Op
from jepsen_tpu.testing import atom_test, simulate_register_history


def _ops(n=12, seed=0):
    return simulate_register_history(n, n_procs=3, n_vals=4, seed=seed)


def _write_wal(path, ops, sync="op"):
    j = journal.Journal(path, sync=sync)
    for o in ops:
        j.append(o)
    j.close()
    return j


def _dead_pid():
    """A pid guaranteed dead: a child we already reaped."""
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    return p.pid


def _mark_dead(d, pid=None):
    store.write_state(d, "running")
    st = json.load(open(os.path.join(d, store.RUN_STATE)))
    st["pid"] = pid if pid is not None else _dead_pid()
    with open(os.path.join(d, store.RUN_STATE), "w") as f:
        json.dump(st, f)


class TestWALFormat:
    def test_roundtrip(self, tmp_path):
        ops = _ops(20)
        path = str(tmp_path / "history.wal")
        _write_wal(path, ops)
        h, stats = journal.read_wal(path)
        assert stats == {"records": len(ops), "torn": 0, "corrupt": 0}
        # values survive modulo JSON normalization (tuples -> lists),
        # the same normalization the history.jsonl load path applies
        reloaded = History.from_jsonl("\n".join(
            json.dumps(o.to_dict()) for o in ops))
        assert h == reloaded

    def test_torn_final_line_dropped_silently(self, tmp_path):
        ops = _ops(10)
        path = str(tmp_path / "history.wal")
        _write_wal(path, ops)
        with open(path, "ab") as f:
            f.write(journal.encode_record(ops[0])[:13])  # cut mid-write
        h, stats = journal.read_wal(path)
        assert len(h) == len(ops)
        assert stats["torn"] == 1 and stats["corrupt"] == 0

    def test_crc_mismatch_line_skipped_and_counted(self, tmp_path):
        ops = _ops(10)
        path = str(tmp_path / "history.wal")
        _write_wal(path, ops)
        data = bytearray(open(path, "rb").read())
        lines = bytes(data).split(b"\n")
        # flip a payload byte in the middle record (keep line structure)
        victim = bytearray(lines[4])
        victim[-2] ^= 0x01
        lines[4] = bytes(victim)
        with open(path, "wb") as f:
            f.write(b"\n".join(lines))
        h, stats = journal.read_wal(path)
        assert len(h) == len(ops) - 1
        assert stats["corrupt"] == 1 and stats["torn"] == 0

    def test_crc_guards_whole_payload(self):
        rec = journal.encode_record(Op(type="invoke", f="read"))
        assert journal.decode_record(rec[:-1]) is not None  # sans \n
        assert journal.decode_record(b"zz" + rec[2:-1]) is None
        assert journal.decode_record(b"") is None
        assert journal.decode_record(b"00000000 {}") is None  # not an op


class TestSyncPolicy:
    def _count_fsyncs(self, monkeypatch):
        calls = []
        real = os.fsync
        monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd),
                                                     real(fd))[1])
        return calls

    def test_sync_op_fsyncs_every_append(self, tmp_path, monkeypatch):
        calls = self._count_fsyncs(monkeypatch)
        _write_wal(str(tmp_path / "w"), _ops(8), sync="op")
        assert len(calls) >= 8

    def test_sync_batch_fsyncs_by_window(self, tmp_path, monkeypatch):
        calls = self._count_fsyncs(monkeypatch)
        j = journal.Journal(str(tmp_path / "w"), sync="batch",
                            batch_s=3600.0)
        for o in _ops(8):
            j.append(o)
        assert len(calls) == 0  # window never elapsed
        j.close()
        assert len(calls) == 1  # the close() flush

    def test_sync_off_never_fsyncs(self, tmp_path, monkeypatch):
        calls = self._count_fsyncs(monkeypatch)
        _write_wal(str(tmp_path / "w"), _ops(8), sync="off")
        assert len(calls) == 0
        # still readable: appends are flushed to the OS regardless
        h, stats = journal.read_wal(str(tmp_path / "w"))
        assert stats["records"] == 16  # 8 ops = invoke+completion pairs

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("JTPU_WAL_SYNC", "op")
        assert journal.sync_policy() == "op"
        monkeypatch.setenv("JTPU_WAL_SYNC", "bogus")
        assert journal.sync_policy() == "batch"  # default on nonsense
        monkeypatch.setenv("JTPU_WAL_BATCH_MS", "250")
        assert journal.batch_window_s() == 0.25
        monkeypatch.setenv("JTPU_WAL", "0")
        assert not journal.enabled()
        assert journal.open_journal("/tmp") is None
        monkeypatch.delenv("JTPU_WAL")
        assert journal.enabled()


class TestReconcile:
    def test_dangling_invoke_becomes_info(self):
        h = History.of([
            {"type": "invoke", "f": "write", "value": 1, "process": 0,
             "time": 10},
            {"type": "ok", "f": "write", "value": 1, "process": 0,
             "time": 20},
            {"type": "invoke", "f": "cas", "value": [1, 2], "process": 1,
             "time": 30},
        ])
        out, n = journal.reconcile(h)
        assert n == 1 and len(out) == 4
        tail = out[-1]
        assert tail.type == "info" and tail.f == "cas"
        assert tail.process == 1 and "wal-recovery" in tail.error
        assert len(h) == 3  # input not mutated

    def test_clean_history_untouched(self):
        h = _ops(10)
        out, n = journal.reconcile(h)
        assert n == 0 and list(out) == list(h)

    def test_reincarnated_process_only_latest_invoke_dangles(self):
        # an info completion abandons the process; the next invoke on
        # p + concurrency is a different process id, so only genuinely
        # open invocations reconcile
        h = History.of([
            {"type": "invoke", "f": "write", "value": 1, "process": 0},
            {"type": "info", "f": "write", "value": 1, "process": 0},
            {"type": "invoke", "f": "read", "value": None, "process": 5},
        ])
        out, n = journal.reconcile(h)
        assert n == 1 and out[-1].process == 5


class TestFromJsonlTolerance:
    def test_skips_and_counts_bad_lines(self):
        good = json.dumps({"type": "invoke", "f": "read", "process": 0})
        text = "\n".join([good, "{truncated", good, '["not a dict"]'])
        h = History.from_jsonl(text)
        assert len(h) == 2
        assert h.decode_errors == 2

    def test_clean_text_counts_zero(self):
        h = History.from_jsonl(_ops(5).to_jsonl())
        assert h.decode_errors == 0 and len(h) == 10


class TestAtomicStore:
    def test_no_tmp_residue_after_save(self, tmp_path):
        d = str(tmp_path / "run")
        os.makedirs(d)
        test = {"store-dir": d, "name": "t",
                "history": _ops(6), "results": {"valid": True}}
        store.save_1(test)
        store.save_2(test)
        files = os.listdir(d)
        assert not [f for f in files if ".tmp." in f], files
        assert {"test.json", "results.json", "history.jsonl",
                "history.txt"} <= set(files)
        assert json.load(open(os.path.join(d, "results.json")))["valid"] \
            is True

    def test_atomic_write_replaces_not_truncates(self, tmp_path,
                                                 monkeypatch):
        # simulate a crash between tmp-write and replace: the original
        # artifact must be intact
        path = str(tmp_path / "results.json")
        store._atomic_write(path, '{"valid": true}')
        monkeypatch.setattr(os, "replace",
                            lambda a, b: (_ for _ in ()).throw(
                                OSError("crash")))
        with pytest.raises(OSError):
            store._atomic_write(path, '{"valid": fal')
        monkeypatch.undo()
        assert json.load(open(path))["valid"] is True

    def test_latest_symlink_swap(self, tmp_path):
        root = tmp_path / "store"
        d1 = root / "t" / "r1"
        d2 = root / "t" / "r2"
        for d in (d1, d2):
            os.makedirs(d)
        store.update_symlinks({"store-dir": str(d1)})
        store.update_symlinks({"store-dir": str(d2)})
        latest = root / "t" / "latest"
        assert os.path.islink(latest)
        assert os.path.realpath(latest) == os.path.realpath(d2)
        assert not [f for f in os.listdir(root / "t") if ".tmp." in f]


class TestRunStateLifecycle:
    def test_clean_run_tees_wal_and_lands_done(self, tmp_path):
        d = str(tmp_path / "atom-cas" / "r1")
        t = atom_test()
        t["store-dir"] = d
        t["generator"] = gen.clients(
            gen.stagger(0.001, gen.limit(25, gen.cas_gen())))
        out = core.run(t)
        assert out["results"]["valid"] is True
        assert store.run_status(d) == "done"
        h, stats = journal.read_wal(os.path.join(d, journal.WAL_NAME))
        assert stats == {"records": len(out["history"]), "torn": 0,
                         "corrupt": 0}
        # the WAL is a tee, not a rewrite: history.jsonl is byte-for-byte
        # what the pre-WAL path wrote
        jl = open(os.path.join(d, "history.jsonl")).read()
        expect = "\n".join(
            json.dumps(o.to_dict(), default=store._json_default)
            for o in out["history"]) + "\n"
        assert jl == expect

    def test_live_run_is_not_dead(self, tmp_path):
        d = str(tmp_path / "t" / "r1")
        os.makedirs(d)
        store.write_state(d, "running")  # records OUR (live) pid
        assert store.run_status(d) == "running"
        assert store.dead_runs(str(tmp_path)) == []

    def test_pre_wal_run_has_no_status(self, tmp_path):
        d = str(tmp_path / "t" / "r1")
        os.makedirs(d)
        assert store.run_status(d) is None
        assert store.dead_runs(str(tmp_path)) == []


@pytest.mark.chaos
class TestRecoverEndToEnd:
    def _dead_run(self, root, torn=True, seed=3):
        d = os.path.join(root, "synthetic", "r1")
        os.makedirs(d)
        h = simulate_register_history(40, n_procs=3, n_vals=4, seed=seed)
        _write_wal(os.path.join(d, journal.WAL_NAME), h[:-1])
        if torn:
            with open(os.path.join(d, journal.WAL_NAME), "ab") as f:
                f.write(journal.encode_record(h[-1])[:15])
        _mark_dead(d)
        return d

    def test_recover_scan_to_verdict(self, tmp_path):
        root = str(tmp_path)
        d = self._dead_run(root)
        assert store.dead_runs(root) == [d]
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store-root", root])
        out = buf.getvalue()
        assert rc == cli.OK
        assert "# recovery:" in out and "torn" in out
        res = json.load(open(os.path.join(d, "results.json")))
        assert res["valid"] is True
        assert store.run_status(d) == "recovered"
        # the reconstructed history is a standard artifact: analyzable
        loaded = store.load(d)
        assert len(loaded["history"]) > 0
        assert loaded["history"].decode_errors == 0

    def test_recover_specific_dir_and_dangling_invokes(self, tmp_path):
        root = str(tmp_path)
        d = os.path.join(root, "synthetic", "r1")
        os.makedirs(d)
        ops = History.of([
            {"type": "invoke", "f": "write", "value": 1, "process": 0,
             "time": 1},
            {"type": "ok", "f": "write", "value": 1, "process": 0,
             "time": 2},
            {"type": "invoke", "f": "read", "value": None, "process": 1,
             "time": 3},
        ])
        _write_wal(os.path.join(d, journal.WAL_NAME), ops)
        _mark_dead(d)
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store", d])
        assert rc == cli.OK
        assert "1 dangling invoke(s)" in buf.getvalue()
        recovered = store.load(d)["history"]
        infos = [o for o in recovered if o.type == "info"]
        assert len(infos) == 1 and infos[0].process == 1

    def test_recover_refuses_done_and_running_runs(self, tmp_path):
        root = str(tmp_path)
        d = os.path.join(root, "t", "r1")
        os.makedirs(d)
        store.write_state(d, "done")
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store", d])
        assert rc == cli.OK and "nothing to recover" in buf.getvalue()
        store.write_state(d, "running")  # our live pid
        with contextlib.redirect_stdout(io.StringIO()):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store", d])
        assert rc == cli.INVALID_ARGS

    def test_recover_without_wal_fails_loudly(self, tmp_path):
        root = str(tmp_path)
        d = os.path.join(root, "t", "r1")
        os.makedirs(d)
        _mark_dead(d)
        buf_err = io.StringIO()
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(buf_err):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store-root", root])
        assert rc == cli.TEST_FAILED
        assert "nothing to recover" in buf_err.getvalue()

    def test_no_analyze_reconstructs_only(self, tmp_path):
        root = str(tmp_path)
        d = self._dead_run(root)
        with contextlib.redirect_stdout(io.StringIO()):
            rc = cli.run(cli.default_commands(),
                         ["recover", "--store-root", root,
                          "--no-analyze"])
        assert rc == cli.OK
        assert os.path.exists(os.path.join(d, "history.jsonl"))
        assert not os.path.exists(os.path.join(d, "results.json"))


@pytest.mark.chaos
class TestHealProbes:
    def _ngen(self):
        yield gen.sleep(0.02)
        yield gen.once({"type": "info", "f": "start"})
        yield gen.sleep(0.02)
        yield gen.once({"type": "info", "f": "stop"})

    def _run(self, nem):
        t = atom_test()
        t["store-dir"] = None
        t["nemesis"] = nem
        t["generator"] = gen.time_limit(5, gen.clients(
            gen.stagger(0.02, gen.limit(300, gen.cas_gen())),
            gen.seq(self._ngen())))
        return core.run(t)

    def test_heal_verified_recorded(self):
        nem = nemesis.Noop()
        nem.heal_probe = nemesis.client_ping_probe(deadline_s=1.0)
        out = self._run(nem)
        probes = [o for o in out["history"] if o.f == "heal-verified"]
        assert probes, [o.f for o in out["history"]
                        if o.process == "nemesis"]
        val = probes[0].value
        assert val["verified"] is True
        assert set(val["nodes"]) == set(out["nodes"])
        assert all(v["ok"] for v in val["nodes"].values())

    def test_heal_failed_recorded_with_error(self):
        nem = nemesis.Noop()
        nem.heal_probe = lambda test, op: {"verified": False,
                                           "error": "still partitioned"}
        out = self._run(nem)
        failed = [o for o in out["history"] if o.f == "heal-failed"]
        assert failed and failed[0].error == "still partitioned"
        assert not [o for o in out["history"] if o.f == "heal-verified"]

    def test_probe_only_fires_on_heal_fs(self):
        fired = []
        nem = nemesis.Noop()
        nem.heal_probe = lambda test, op: (fired.append(op.f),
                                           {"verified": True})[1]
        self._run(nem)
        assert fired == ["stop"]  # never on f=start

    def test_broken_probe_is_a_heal_failure_not_a_crash(self):
        nem = nemesis.Noop()

        def boom(test, op):
            raise RuntimeError("probe exploded")
        nem.heal_probe = boom
        out = self._run(nem)
        failed = [o for o in out["history"] if o.f == "heal-failed"]
        assert failed and "RuntimeError" in failed[0].value["error"]

    def test_compose_routes_probe_to_handling_child(self):
        routed = []
        child = nemesis.Noop()
        child.heal_probe = lambda test, op: (routed.append(op.f),
                                             {"verified": True})[1]
        comp = nemesis.compose([({"resume": "stop"}, child)])
        r = comp.verify_heal({}, Op(type="info", f="resume"))
        assert routed == ["stop"] and r["verified"] is True
        assert comp.verify_heal({}, Op(type="info", f="start")) is None

    def test_retry_until_deadline_backoff(self):
        from jepsen_tpu.resilience import RetryPolicy, retry_until_deadline
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionResetError("flake")
            return True

        ok, attempts, err = retry_until_deadline(
            flaky, 5.0, policy=RetryPolicy(backoff_base_s=0.001,
                                           backoff_cap_s=0.002))
        assert ok and attempts == 3 and err is None
        ok, attempts, err = retry_until_deadline(
            lambda: False, 0.05,
            policy=RetryPolicy(backoff_base_s=0.01, backoff_cap_s=0.01))
        assert not ok and attempts >= 2 and "falsy" in err
