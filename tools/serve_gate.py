#!/usr/bin/env python
"""CI serve gate: stand the check daemon up, POST a REAL localkv
history at it over HTTP, poll the verdict, drain, and exit — inside a
wall-clock bound (default 30 s, run next to lint_gate / prof_gate /
bench_gate in CI).

The daemon path (`python -m jepsen_tpu serve --check-daemon`,
doc/serve.md) crosses five layers — the HTTP front-end, admission
control, the request journal, the warm-engine check execution, and
drain — and a regression in any of them only surfaces on a real
served request. This gate IS that request:

* a real localkv suite (real daemons, real sockets) produces a real
  history;
* the daemon admits it (202 + id), checks it on the warm device path,
  and the polled verdict must be ``valid: true`` AND identical to the
  offline ``analyze``-path verdict computed in-process;
* a same-bucket burst must coalesce: the gang scheduler has to
  dispatch at least one batch of size >= 2 (healthz ``stats.batches``
  / ``stats.max-batch``), proving concurrent batching survives CI;
* ``/healthz`` must report the completed request and a warm bucket;
* request tracing must span the whole path: the 202 carries a trace id
  (echoed as a ``traceparent`` header), the done verdict carries a
  phase breakdown, the daemon's trace.jsonl holds >= 4 distinct span
  names under that ONE trace id (admission -> warm/compile -> device
  segment -> verdict), and at least one /metrics histogram bucket
  carries an OpenMetrics exemplar pointing at a trace id;
* the streaming intake must survive CI at scale: a 10k-op stream built
  on the real localkv history goes in as CRC'd sequenced chunks
  (``POST /stream`` / ``/stream/<id>/ops`` / ``/close``,
  doc/serve.md "Streaming API"), the online checker's verdict must be
  ``valid: true`` AND identical to the offline verdict over the same
  ops, and ``/healthz`` must report the session;
* ``POST /drain`` must finish in-flight work and release the daemon
  (exit-0 contract);
* a SECOND daemon stands up fleet-backed (``--fleet 2``, two real
  ``ProcHost`` worker processes): a saturating multi-tenant same-bucket
  burst must shard over both workers, ``/healthz`` must report
  ``fleet.live == 2``, every verdict must equal the offline path's, and
  drain must release it — proving fleet-backed serving survives CI
  (doc/serve.md, "Fleet-backed serving");
* the telemetry layer must reconcile: after the saturating burst,
  ``GET /usage`` totals must equal a fold over the WAL's ``done``
  records digit for digit, and ``GET /slo`` must answer every declared
  objective with a finite burn rate for every window
  (doc/observability.md, "Usage metering" / "SLOs");
* the federated telemetry plane must span the fleet: BOTH ProcHost
  workers' telemetry frames must be folded into the daemon's ONE
  tsdb under their ``host=`` labels, and ``GET /trace/find`` must
  resolve a burst request by tenant across the mesh
  (doc/observability.md, "Fleet federation").

Usage: python tools/serve_gate.py [--budget SECONDS] [--time-limit S]
Exit code 0 iff the served verdict matches offline within the budget.
"""

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _post(port, path, doc):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode() if doc is not None else b"",
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, json.load(r)


def _get_text(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", type=float, default=30.0,
                    help="wall-clock bound for the whole gate (s)")
    ap.add_argument("--time-limit", type=int, default=3,
                    help="localkv workload seconds")
    args = ap.parse_args()
    t0 = time.time()

    from jepsen_tpu import core, serve as serve_ns
    from jepsen_tpu.suites.localkv import localkv_test

    # 1. a REAL history from a real localkv run
    root = tempfile.mkdtemp(prefix="jepsen-serve-gate-")
    test = localkv_test({"time-limit": args.time_limit,
                         "nemesis-period": 2})
    test["store-dir"] = os.path.join(root, "local-kv", "run")
    test = core.run(test)
    history = [op.to_dict() for op in test["history"]]
    if not history:
        print("# serve-gate: FAILED — localkv produced no history",
              file=sys.stderr)
        return 1

    # 2. the daemon, on a real port — coalesce window widened so the
    # same-bucket burst below reliably forms a gang inside CI jitter
    cfg = serve_ns.ServeConfig(root=os.path.join(root, "serve"),
                               backend="tpu", batch_wait_ms=250.0)
    daemon, server = serve_ns.run_daemon(
        cfg, host="127.0.0.1", port=0, store_root=root)
    port = server.server_port
    problems = []
    verdict = None
    try:
        code, body, hdrs = _post(
            port, "/check", {"tenant": "gate",
                             "model": "cas-register",
                             "history": history})
        if code != 202:
            problems.append(f"POST /check answered {code}: {body}")
        else:
            rid = body["id"]
            trace_id = body.get("trace")
            if not trace_id:
                problems.append("202 body carries no trace id")
            echoed = (hdrs.get("traceparent") or "")
            if trace_id and trace_id not in echoed:
                problems.append(
                    f"traceparent header {echoed!r} does not echo "
                    f"trace {trace_id}")
            deadline = time.time() + args.budget
            doc = {}
            while time.time() < deadline:
                _, doc = _get(port, f"/check/{rid}")
                if doc.get("state") == "done":
                    break
                time.sleep(0.1)
            if doc.get("state") != "done":
                problems.append(f"request never finished: {doc}")
            else:
                verdict = doc["result"].get("valid")
                if verdict is not True:
                    problems.append(
                        f"served verdict {verdict!r}, want True")
                # the crash-safety equality leg: served == offline
                from jepsen_tpu.checker import check_safe
                from jepsen_tpu.checker.wgl import linearizable
                from jepsen_tpu.history import History
                from jepsen_tpu.models import CASRegister
                offline = check_safe(
                    linearizable(CASRegister(), backend="tpu"),
                    {"name": "serve-gate-offline"},
                    History.of(history))
                if offline.get("valid") != verdict:
                    problems.append(
                        f"served verdict {verdict!r} != offline "
                        f"{offline.get('valid')!r}")
                # the tracing leg: one trace id spans POST -> verdict
                serve_doc = doc["result"].get("serve", {})
                phases = serve_doc.get("phases", {})
                want = {"queue_s", "coalesce_s", "compile_s",
                        "device_s", "verdict_s"}
                if not want <= set(phases):
                    problems.append(
                        f"phase breakdown incomplete: {phases}")
                if serve_doc.get("trace") != trace_id:
                    problems.append(
                        f"verdict carries trace "
                        f"{serve_doc.get('trace')!r}, admission "
                        f"promised {trace_id!r}")
                from jepsen_tpu.obs import trace as trace_ns
                trecs, _ = trace_ns.read_trace(
                    os.path.join(cfg.root, trace_ns.TRACE_NAME))
                names = {r["name"] for r in trecs
                         if r.get("trace") == trace_id}
                if len(names) < 4:
                    problems.append(
                        f"trace {trace_id} spans only {sorted(names)}"
                        f", want >= 4 phases POST -> verdict")
                if not {"serve.request", "serve.verdict"} <= names:
                    problems.append(
                        f"trace {trace_id} missing admission/verdict "
                        f"spans: {sorted(names)}")
        # 3. the gang scheduler: a same-bucket burst must coalesce into
        # at least one batched dispatch of size >= 2 (doc/serve.md,
        # "Concurrent batching") — the first request warmed the bucket,
        # so the burst exercises the batched device path end to end
        burst = []
        for i in range(3):
            code, body, _ = _post(port, "/check",
                                  {"tenant": f"burst-{i % 2}",
                                   "model": "cas-register",
                                   "history": history})
            if code == 202:
                burst.append(body["id"])
            else:
                problems.append(f"burst POST {i} answered {code}: "
                                f"{body}")
        deadline = time.time() + args.budget
        while time.time() < deadline and burst:
            burst = [r for r in burst
                     if _get(port, f"/check/{r}")[1].get("state")
                     != "done"]
            time.sleep(0.05)
        if burst:
            problems.append(f"{len(burst)} burst request(s) never "
                            f"finished")
        _, health = _get(port, "/healthz")
        stats = health.get("stats", {})
        if not stats.get("batches"):
            problems.append(f"burst dispatched no batch: {stats}")
        elif stats.get("max-batch", 0) < 2:
            problems.append(f"no gang of size >= 2 formed: {stats}")
        if not stats.get("completed"):
            problems.append(f"healthz reports no completed request: "
                            f"{stats}")
        if not health.get("engine", {}).get("warm-buckets"):
            problems.append("healthz reports no warm bucket")
        if "oldest-inflight-s" not in health:
            problems.append("healthz lost the oldest-inflight-s field")
        _, metrics_text = _get_text(port, "/metrics")
        if ' # {trace_id="' not in metrics_text:
            problems.append("no OpenMetrics exemplar on any /metrics "
                            "histogram bucket")
        # 3b. the streaming leg: a 10k-op stream built on the SAME real
        # localkv history (extended with a sequential write/read tail on
        # a fresh process, which keeps the combined single-register
        # history valid) goes in as CRC'd sequenced chunks, and the
        # online checker's verdict must equal the offline verdict over
        # the same ops (doc/serve.md, "Streaming API")
        from jepsen_tpu import stream as stream_ns
        stream_ops = list(history)
        t_next = 1 + max((op.get("time") or 0) for op in history)
        i_next = len(history)
        proc = 1 + max((op.get("process") or 0) for op in history
                       if isinstance(op.get("process"), int))
        value = 1_000_000
        while len(stream_ops) < 10_000:
            for f, val, typ in (("write", value, "invoke"),
                                ("write", value, "ok"),
                                ("read", None, "invoke"),
                                ("read", value, "ok")):
                stream_ops.append({"type": typ, "f": f, "value": val,
                                   "process": proc, "time": t_next,
                                   "index": i_next})
                t_next += 1
                i_next += 1
            value += 1
        chunks = [stream_ops[i:i + 500]
                  for i in range(0, len(stream_ops), 500)]
        code, body, _ = _post(port, "/stream",
                              {"tenant": "gate-stream",
                               "model": "cas-register"})
        if code != 202:
            problems.append(f"POST /stream answered {code}: {body}")
        else:
            sid = body["id"]
            seq = 0
            deadline = time.time() + args.budget
            while seq < len(chunks) and time.time() < deadline:
                code, body, _ = _post(
                    port, f"/stream/{sid}/ops",
                    {"seq": seq, "ops": chunks[seq],
                     "crc": stream_ns.chunk_crc(chunks[seq])})
                if code == 202:
                    seq += 1
                elif code == 429:
                    time.sleep(float(body.get("retry-after-s", 0.2)))
                elif code == 409 and "need" in body:
                    seq = int(body["need"])
                else:
                    problems.append(f"stream chunk {seq} answered "
                                    f"{code}: {body}")
                    break
            code, body, _ = _post(port, f"/stream/{sid}/close",
                                  {"chunks": len(chunks)})
            if code != 200:
                problems.append(f"stream close answered {code}: {body}")
            sdoc = {}
            deadline = time.time() + args.budget
            while time.time() < deadline:
                _, sdoc = _get(port, f"/stream/{sid}")
                if sdoc.get("state") == "done" and "result" in sdoc:
                    break
                time.sleep(0.1)
            if sdoc.get("state") != "done" or "result" not in sdoc:
                problems.append(f"stream never finished: state="
                                f"{sdoc.get('state')!r}")
            else:
                from jepsen_tpu.checker import check_safe
                from jepsen_tpu.checker.wgl import linearizable
                from jepsen_tpu.history import History
                from jepsen_tpu.models import CASRegister
                offline_stream = check_safe(
                    linearizable(CASRegister(), backend="tpu"),
                    {"name": "serve-gate-stream-offline"},
                    History.of(stream_ops))
                got = sdoc["result"].get("valid")
                if got is not True:
                    problems.append(f"streamed verdict {got!r}, "
                                    f"want True")
                if got != offline_stream.get("valid"):
                    problems.append(
                        f"streamed verdict {got!r} != offline "
                        f"{offline_stream.get('valid')!r} over the "
                        f"same {len(stream_ops)} ops")
                _, health = _get(port, "/healthz")
                sm = health.get("streams") or {}
                if not sm.get("sessions"):
                    problems.append(
                        f"healthz reports no stream session: {sm}")
                print(f"# serve-gate: streamed {len(stream_ops)} ops "
                      f"in {len(chunks)} chunk(s), verdict matches "
                      f"offline")
        code, drained, _ = _post(port, "/drain", None)
        if code != 200 or not drained.get("drained"):
            problems.append(f"drain answered {code}: {drained}")
        if not daemon.drained.wait(timeout=5):
            problems.append("drain did not release the daemon")
    finally:
        server.shutdown()
        daemon.stop()

    # 4. the fleet leg: a second daemon with 2 REAL ProcHost workers;
    # a saturating multi-tenant burst (more requests than hosts) must
    # land every verdict, and healthz must show both hosts live
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history
    # a SEEDED history for the burst: the localkv draw above is
    # timing-random, and an unlucky draw escalates the gang planner to
    # a cap-512 rung whose XLA compile alone (~35 s) blows the gate
    # budget — the fleet leg gates dispatch/federation plumbing, not
    # plan escalation, so its shape must be deterministic
    fleet_hist = [op.to_dict() for op in
                  simulate_register_history(300, n_procs=4, n_vals=3,
                                            seed=7)]
    offline_valid = check_safe(
        linearizable(CASRegister(), backend="tpu"),
        {"name": "serve-gate-fleet-offline"},
        History.of(fleet_hist)).get("valid")
    # short telemetry cadences so the federation leg below sees both
    # workers' frames folded well inside the gate budget
    os.environ.setdefault("JTPU_FED_CADENCE", "0.25")
    fcfg = serve_ns.ServeConfig(root=os.path.join(root, "serve-fleet"),
                                backend="tpu", batch_wait_ms=250.0,
                                fleet_hosts=2, fleet_backend="proc",
                                tsdb_cadence_s=0.5)
    fdaemon, fserver = serve_ns.run_daemon(
        fcfg, host="127.0.0.1", port=0, store_root=root)
    fport = fserver.server_port
    try:
        if fdaemon.placer is None:
            problems.append("fleet daemon built no placer")
        fburst = []
        for i in range(6):
            code, body, _ = _post(fport, "/check",
                                  {"tenant": f"fleet-{i % 3}",
                                   "model": "cas-register",
                                   "history": fleet_hist})
            if code == 202:
                fburst.append(body["id"])
            else:
                problems.append(f"fleet POST {i} answered {code}: "
                                f"{body}")
        deadline = time.time() + args.budget
        pending = list(fburst)
        while time.time() < deadline and pending:
            pending = [r for r in pending
                       if _get(fport, f"/check/{r}")[1].get("state")
                       != "done"]
            time.sleep(0.05)
        if pending:
            problems.append(f"{len(pending)} fleet request(s) never "
                            f"finished")
        for r in fburst:
            if r in pending:
                continue
            _, doc = _get(fport, f"/check/{r}")
            got = doc.get("result", {}).get("valid")
            if got != offline_valid:
                problems.append(f"fleet verdict {got!r} != offline "
                                f"{offline_valid!r}")
        _, fhealth = _get(fport, "/healthz")
        fl = fhealth.get("fleet", {})
        if fl.get("live") != 2 or fl.get("hosts") != 2:
            problems.append(f"healthz fleet {fl}, want 2/2 proc hosts")
        if not fl.get("gangs"):
            problems.append(f"fleet dispatched no gang: {fl}")
        # 4b. the telemetry leg: after the saturating burst, the usage
        # meter's live totals must equal a fold over the WAL's done
        # records (doc/observability.md, "Usage metering"), and /slo
        # must answer every declared objective with a finite burn rate
        from jepsen_tpu.obs import usage as usage_ns
        code, usage_doc = _get(fport, "/usage")
        if code != 200:
            problems.append(f"GET /usage answered {code}")
        else:
            wal_totals = usage_ns.from_wal(
                os.path.join(fcfg.root, serve_ns.WAL_NAME))
            if usage_doc != wal_totals:
                problems.append(
                    f"live usage {usage_doc} != WAL fold {wal_totals}")
            tenants = usage_doc.get("tenants", {})
            if len(tenants) < 3:
                problems.append(
                    f"usage meter saw {sorted(tenants)}, want the 3 "
                    f"burst tenants")
        code, slo_doc = _get(fport, "/slo")
        if code != 200:
            problems.append(f"GET /slo answered {code}")
        else:
            objectives = slo_doc.get("objectives", {})
            if not objectives:
                problems.append(f"/slo declares no objectives: "
                                f"{slo_doc}")
            for name, obj in objectives.items():
                windows = obj.get("windows") or {}
                if not windows:
                    problems.append(f"objective {name} answers no "
                                    f"windows: {obj}")
                for win, burn in windows.items():
                    if not (isinstance(burn, (int, float))
                            and burn == burn
                            and abs(burn) != float("inf")):
                        problems.append(
                            f"objective {name} window {win} burn "
                            f"{burn!r} is not finite")
        # 4c. the federation leg: both ProcHost workers export
        # telemetry frames; the daemon's federator must fold them into
        # the ONE tsdb under per-host labels, and trace search must
        # resolve a burst request by tenant across the mesh
        # (doc/observability.md, "Fleet federation")
        if fdaemon.federator is None:
            problems.append("fleet daemon built no federator")
        else:
            want_hosts = {"fleet-host-0", "fleet-host-1"}
            deadline = time.time() + args.budget
            labeled = set()
            while time.time() < deadline:
                labeled = set()
                series = fdaemon.tsdb.recent(600.0).get("series", {})
                for doc in series.values():
                    for sk in doc:
                        for h in want_hosts:
                            if f'host="{h}"' in sk:
                                labeled.add(h)
                if want_hosts <= labeled:
                    break
                time.sleep(0.1)
            fed_hosts = set(fdaemon.federator.hosts())
            if not want_hosts <= fed_hosts:
                problems.append(
                    f"federator ingested frames from "
                    f"{sorted(fed_hosts)}, want both of "
                    f"{sorted(want_hosts)}")
            if not want_hosts <= labeled:
                problems.append(
                    f"federated tsdb holds host-labeled series for "
                    f"{sorted(labeled)}, want both of "
                    f"{sorted(want_hosts)}")
            code, tf = _get(fport,
                            "/trace/find?tenant=fleet-0&format=json")
            if code != 200:
                problems.append(f"GET /trace/find answered {code}")
            else:
                rows = tf.get("requests", [])
                ids = {r.get("id") for r in rows}
                if not ids & set(fburst):
                    problems.append(
                        f"trace find by tenant resolved "
                        f"{sorted(ids)}, none of the burst ids")
                if any(r.get("tenant") != "fleet-0" for r in rows):
                    problems.append(
                        f"trace find leaked a foreign tenant: {rows}")
        code, drained, _ = _post(fport, "/drain", None)
        if code != 200 or not drained.get("drained"):
            problems.append(f"fleet drain answered {code}: {drained}")
    finally:
        fserver.shutdown()
        fdaemon.stop()

    wall = time.time() - t0
    if wall > args.budget:
        problems.append(f"gate overran its {args.budget:.0f}s budget "
                        f"({wall:.1f}s)")
    print(f"# serve-gate: {len(history)} op(s) served, verdict="
          f"{verdict!r}, {wall:.1f}s")
    if problems:
        for p in problems:
            print(f"# serve-gate: FAILED — {p}", file=sys.stderr)
        return 1
    print("# serve-gate: served verdict matches the offline path; "
          "drain released the daemon")
    return 0


if __name__ == "__main__":
    sys.exit(main())
