#!/usr/bin/env python
"""CI lint gate: run the four-pass static analyzer over the repo and
exit nonzero on any finding not covered by the committed baseline.

Stricter than ``python -m jepsen_tpu lint`` (whose exit code gates on
new *errors* only): CI should not accumulate new warnings silently
either — either fix them or accept them into ``lint.baseline`` with a
one-line justification.

Usage: python tools/lint_gate.py [--baseline FILE] [--root DIR]
Exit code 0 iff the tree is clean against the baseline.
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jepsen_tpu import analysis  # noqa: E402
from jepsen_tpu.analysis import baseline as bl  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: lint.baseline at the "
                         "repo root)")
    ap.add_argument("--root", default=None, help="repo root override")
    args = ap.parse_args()

    root = args.root or REPO
    bpath = args.baseline or bl.default_path(root)
    findings = analysis.lint_repo(root=root)
    accepted_keys = bl.load(bpath)
    new, accepted = bl.split(findings, accepted_keys)

    # A baseline entry that no longer matches anything is stale — warn
    # so accepted debt gets cleaned out when the finding is fixed.
    live = {f.key() for f in findings}
    stale = [k for k in accepted_keys if k not in live]
    for k in stale:
        print(f"# lint-gate: stale baseline entry (fixed? remove it): "
              f"{k}")

    for f in sorted(new, key=lambda x: (x.path, x.line)):
        print(f.format())
    print(analysis.summary_line(new))
    if accepted:
        print(f"# lint-gate: {len(accepted)} finding(s) accepted by "
              f"{bpath}")
    if new:
        print(f"# lint-gate: FAILED — {len(new)} new finding(s) not in "
              f"the baseline; fix them or accept them with a "
              f"justification", file=sys.stderr)
        return 1
    print("# lint-gate: clean against the baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
