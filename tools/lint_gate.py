#!/usr/bin/env python
"""CI lint gate: run the full static-analyzer pass set over the repo
and exit nonzero on any finding not covered by the committed baseline.

Stricter than ``python -m jepsen_tpu lint`` (whose exit code gates on
new *errors* only): CI should not accumulate new warnings silently
either — either fix them or accept them into ``lint.baseline`` with a
one-line justification.

On top of the repo scan this gate runs the **traced plan fixture
matrix** (``jepsen_tpu.analysis.plan_lint.PLAN_MATRIX``): every
integer-kernel model family at representative history dims, each shape
bucket abstract-evaluated with ``jax.eval_shape`` — so a kernel- or
search-shape regression that would break a bucket fails CI in seconds,
on CPU, with zero XLA compiles, instead of failing on device minutes
into a run. ``--no-plan`` skips the traced matrix (the arithmetic
matrix still runs inside the repo scan).

Stale baseline entries (accepted debt that was since fixed) warn, and
the warnings ESCALATE: a sidecar counter file next to the baseline
(``<baseline>.stale``) tracks how many consecutive gate runs each
entry has been stale; past ``--stale-grace`` runs (default 3) the gate
fails until someone runs ``python -m jepsen_tpu lint --prune-stale``.
A clean run deletes the sidecar.

Usage: python tools/lint_gate.py [--baseline FILE] [--root DIR]
                                 [--sarif OUT] [--no-plan]
                                 [--stale-grace N]
Exit code 0 iff the tree is clean against the baseline.
``--sarif OUT`` additionally writes the new findings as SARIF 2.1.0
(doc/lint.md) so CI can annotate the pull request inline.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu import analysis  # noqa: E402
from jepsen_tpu.analysis import baseline as bl  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: lint.baseline at the "
                         "repo root)")
    ap.add_argument("--root", default=None, help="repo root override")
    ap.add_argument("--sarif", default=None, metavar="OUT",
                    help="also write the new findings as SARIF 2.1.0 "
                         "(forge PR annotation)")
    ap.add_argument("--no-plan", action="store_true",
                    help="skip the traced plan fixture matrix (the "
                         "arithmetic plan pass still runs)")
    ap.add_argument("--stale-grace", type=int, default=3, metavar="N",
                    help="fail once a baseline entry has been stale "
                         "for more than N consecutive gate runs "
                         "(default: 3; prune with 'python -m "
                         "jepsen_tpu lint --prune-stale')")
    args = ap.parse_args()

    root = args.root or REPO
    bpath = args.baseline or bl.default_path(root)
    findings = analysis.lint_repo(root=root)
    if not args.no_plan:
        # Upgrade the repo scan's arithmetic plan rows to the traced
        # variant: every bucket in the pinned matrix must still
        # abstract-evaluate (jax.eval_shape; zero compiles).
        from jepsen_tpu.analysis import plan_lint
        t0 = time.time()
        traced = plan_lint.lint_matrix(trace=True)
        findings = ([f for f in findings if not f.path.startswith("plan:")]
                    + traced)
        print(f"# lint-gate: plan matrix traced "
              f"({len(plan_lint.PLAN_MATRIX)} row(s) in "
              f"{time.time() - t0:.1f}s, zero XLA compiles)")
    accepted_keys = bl.load(bpath)
    new, accepted = bl.split(findings, accepted_keys)

    # A baseline entry that no longer matches anything is stale — warn
    # so accepted debt gets cleaned out when the finding is fixed. The
    # warnings escalate: the sidecar counts consecutive stale runs per
    # key, and past the grace the gate fails until a prune.
    live = {f.key() for f in findings}
    stale = [k for k in accepted_keys if k not in live]
    sidecar = bpath + ".stale"
    counts = {}
    if os.path.exists(sidecar):
        try:
            with open(sidecar, encoding="utf-8") as f:
                counts = {k: int(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            counts = {}
    counts = {k: counts.get(k, 0) + 1 for k in stale}
    if stale:
        try:
            with open(sidecar, "w", encoding="utf-8") as f:
                json.dump(counts, f, indent=0, sort_keys=True)
        except OSError:
            pass
    elif os.path.exists(sidecar):
        try:
            os.unlink(sidecar)
        except OSError:
            pass
    stale_over = sorted(k for k, n in counts.items()
                        if n > args.stale_grace)
    for k in stale:
        print(f"# lint-gate: stale baseline entry (fixed? remove it): "
              f"{k} [{counts[k]}/{args.stale_grace} warning(s)]")

    for f in sorted(new, key=lambda x: (x.path, x.line)):
        print(f.format())
    print(analysis.summary_line(new))
    if accepted:
        print(f"# lint-gate: {len(accepted)} finding(s) accepted by "
              f"{bpath}")
    if args.sarif:
        from jepsen_tpu.analysis import sarif
        sarif.write(args.sarif, new)
        print(f"# lint-gate: wrote SARIF ({len(new)} new finding(s)) "
              f"to {args.sarif}")
    if new:
        print(f"# lint-gate: FAILED — {len(new)} new finding(s) not in "
              f"the baseline; fix them or accept them with a "
              f"justification", file=sys.stderr)
        return 1
    if stale_over:
        print(f"# lint-gate: FAILED — {len(stale_over)} baseline "
              f"entr{'y' if len(stale_over) == 1 else 'ies'} stale "
              f"past the {args.stale_grace}-run grace; run 'python -m "
              f"jepsen_tpu lint --prune-stale'", file=sys.stderr)
        return 1
    print("# lint-gate: clean against the baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
