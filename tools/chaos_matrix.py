#!/usr/bin/env python
"""Chaos matrix: sweep the injected-fault grid through the resilient
execution layer and print a pass/fail matrix.

Scenarios (the same grid tests/test_resilience.py covers under the
``chaos`` pytest marker, here runnable standalone on any host — e.g. to
qualify a new accelerator image before trusting it with long runs):

  oom              RESOURCE_EXHAUSTED mid-search: pool halves (with
                   backoff) and the verdict still matches the CPU oracle
  wedge            a wedged device segment: the checkpoint completes on
                   the CPU fallback instead of hanging
  kill-mid-segment a fatal exception after N segments: the saved
                   checkpoint resumes to the identical verdict
  transient        flaky RPC errors: jittered retries, then success
  hung-client      a client.invoke that never returns: op-timeout turns
                   it into :info and the run completes
  kill9-recover    SIGKILL a real localkv run mid-workload: `recover`
                   rebuilds the history from the write-ahead journal
                   and the offline checker renders a verdict
  malformed-history  corrupt a real localkv history three ways
                   (dangling invoke, process reuse, bad op type): the
                   pre-search lint gate rejects each with the right
                   rule id BEFORE any jit compilation; the clean
                   history still checks valid
  trace-integrity  SIGKILL a TRACED localkv run mid-workload: the
                   streamed trace.jsonl survives (tail-tolerant read),
                   and `recover` prints a `# trace:` span-count
                   summary next to its `# lint:`/`# recovery:` lines
  watched-kill     SIGKILL a WATCHED localkv run mid-workload: the
                   /live/<test>/<ts> endpoint still answers (state
                   dead, no 500), the `watch` CLI degrades to a
                   graceful status line, and recovery still renders
                   a verdict
  explain-kill     SIGKILL a localkv run mid-search, then tear its
                   searchstats.json: `recover` still renders a
                   verdict, `jtpu explain` still renders a report,
                   and the web /explain/<test>/<ts> page answers
                   200 (never a 500) from the partial artifacts
  prof-kill        SIGKILL a --profile (JTPU_PROF=1) localkv run while
                   the device profiler is mid-capture: the partial
                   capture reads tail-tolerantly, `recover` still
                   renders a verdict, and `trace export` degrades to
                   valid Chrome JSON
  plan-rejects     drive a real localkv history at an oversized
                   capacity (tiny JTPU_PLAN_BYTES_LIMIT) and at a
                   non-dividing mesh axis: the pre-search plan gate
                   rejects each with PLAN-OOM / PLAN-SHARD-INDIVISIBLE
                   BEFORE any jit factory is invoked; the clean
                   configuration still checks valid
  fleet-host-kill  SIGKILL one host of a 2-process (CPU-simulated DCN)
                   elastic-fleet pool-sharded search mid-rung: the
                   survivor re-meshes at the merge barrier
                   (remesh-to-1-hosts trail event), finishes the
                   search, and the verdict matches the single-host
                   baseline AND the CPU oracle
  straggler-host   deliberately slow ONE worker of a 2-process
                   elastic-fleet search (JTPU_CHAOS_SLOW_HOST stalls
                   it before every shard segment): the straggler
                   observatory flags exactly that host within 3 merge
                   rounds in which it ran a segment
                   (straggler-flagged trail event), the flag
                   forces a steal-rebalance re-deal, the verdict
                   matches the single-host baseline and the CPU
                   oracle, and `jtpu trace find --host` attributes a
                   served burst's requests to the slowed worker
  serve-kill       SIGKILL the check daemon (`jtpu serve`) with one
                   request in-flight and one queued: a restarted
                   daemon replays its request journal (serve.wal),
                   re-checks both, and both verdicts are identical to
                   the offline analyze path
  trace-request-kill  SIGKILL the daemon mid-check on a request
                   admitted with an inbound W3C traceparent: the
                   restarted daemon's journal replay keeps the
                   ORIGINAL trace id (not a fresh mint), the re-run
                   joins the same trace in trace.jsonl, and the
                   single-request stitched waterfall (`jtpu trace
                   request <id>`) still renders end to end
  serve-batch-poison  a 4-request same-bucket burst with ONE poison
                   member OOMing every gang that contains it: the gang
                   scheduler bisects to isolate it — 3 survivors
                   answer 200 with offline-identical verdicts, the
                   poison answers 500 (oom), and its bucket's breaker
                   counts exactly one failure
  stream-kill      SIGKILL the daemon MID-STREAM after the online
                   checker saved a partial-verdict checkpoint: the
                   restarted daemon replays the per-session WAL,
                   resumes the search from the checkpointed level
                   (never level 0), and the sealed stream's verdict is
                   identical to the offline analyze path
  stream-dup       a duplicate / out-of-order chunk storm (every chunk
                   twice, pairs swapped, re-post after close): the
                   sealed history.json is byte-identical to a clean
                   in-order session's and the verdict matches offline
  flightrec-kill   SIGKILL the daemon mid-burst after a poison request
                   tripped its bucket's breaker: the breaker-trip
                   flight-recorder dump written before the kill is
                   whole (valid JSON, atomic rename), carries the
                   poison's trace id, and renders via `jtpu
                   flightrec`; the SIGTERM-path dump is absent
  lint-seeded-race patch a known-bad pattern (off-lock queue append +
                   depth bump) into a COPY of serve.py and assert the
                   lockset static-analysis pass fires LOCK-UNGUARDED
                   on exactly the seeded method — proving the analyzer
                   catches the bug class that motivated it

Usage: python tools/chaos_matrix.py [--seed N] [--only NAME ...]
Exit code 0 iff every selected scenario passes — nonzero on any
regression, so this sweep can gate in CI.
"""

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JEPSEN_RETRY_BASE", "0.001")

from jepsen_tpu import accel, resilience  # noqa: E402
from jepsen_tpu.checker.wgl import check_packed  # noqa: E402
from jepsen_tpu.models import CASRegister  # noqa: E402
from jepsen_tpu.ops.encode import pack_with_init  # noqa: E402
from jepsen_tpu.resilience import (  # noqa: E402
    RetryPolicy, WedgeError, supervised_check_packed)
from jepsen_tpu.testing import simulate_register_history  # noqa: E402


def _packed(seed):
    h = simulate_register_history(150, n_procs=5, n_vals=4, seed=seed,
                                  crash_p=0.02)
    return pack_with_init(h, CASRegister())


def _policy():
    return RetryPolicy(backoff_base_s=0.001, backoff_cap_s=0.01)


def scenario_oom(seed):
    p, kernel = _packed(seed)
    oracle = check_packed(p, kernel)["valid"]
    fired = []

    def oom_twice(ctx):
        if ctx["segment"] == 1 and len(fired) < 2:
            fired.append(1)
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    resilience._inject_fault = oom_twice
    try:
        r = supervised_check_packed(p, kernel, capacity=256, expand=16,
                                    segment_iters=8, policy=_policy())
    finally:
        resilience._inject_fault = None
    ok = (r["valid"] == oracle and r["rung"][0] == 64
          and len(fired) == 2)
    return ok, (f"verdict {r['valid']} (oracle {oracle}), pool "
                f"256->{r['rung'][0]}, {len(fired)} OOMs injected")


def scenario_wedge(seed):
    p, kernel = _packed(seed)
    base = supervised_check_packed(p, kernel, capacity=128, expand=8,
                                   segment_iters=8)
    wedged = []

    def wedge_once(ctx):
        if ctx["segment"] == 2 and not wedged:
            wedged.append(1)
            raise WedgeError("injected wedge")

    import warnings
    resilience._inject_fault = wedge_once
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r = supervised_check_packed(p, kernel, capacity=128, expand=8,
                                        segment_iters=8)
    finally:
        resilience._inject_fault = None
        accel._reset_for_tests()
    ok = (r["valid"] == base["valid"] and r["levels"] == base["levels"]
          and r.get("backend-fallback") == "cpu")
    return ok, (f"verdict {r['valid']} == uninterrupted, completed on "
                f"{r.get('backend-fallback')} fallback")


def scenario_kill_mid_segment(seed):
    p, kernel = _packed(seed)
    base = supervised_check_packed(p, kernel, capacity=128, expand=8,
                                   segment_iters=8)
    cps = []

    def killer(ctx):
        if ctx["segment"] == 3:
            raise ValueError("chaos kill")

    resilience._inject_fault = killer
    try:
        try:
            supervised_check_packed(
                p, kernel, capacity=128, expand=8, segment_iters=8,
                policy=RetryPolicy(max_retries=0, backoff_base_s=0.001),
                on_checkpoint=cps.append)
            return False, "kill never fired"
        except ValueError:
            pass
    finally:
        resilience._inject_fault = None
    if not cps:
        return False, "no checkpoints before the kill"
    r = supervised_check_packed(p, kernel, capacity=128, expand=8,
                                segment_iters=8, resume=cps[-1])
    ok = (r["valid"] == base["valid"] and r["levels"] == base["levels"])
    return ok, (f"resumed from segment {cps[-1].segment} -> verdict "
                f"{r['valid']} levels {r['levels']} "
                f"(uninterrupted {base['levels']})")


def scenario_transient(seed):
    p, kernel = _packed(seed)
    base = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                   segment_iters=8)
    flakes = []

    def flaky(ctx):
        if ctx["segment"] == 1 and len(flakes) < 2:
            flakes.append(1)
            raise ConnectionResetError("flaky rpc")

    resilience._inject_fault = flaky
    try:
        r = supervised_check_packed(p, kernel, capacity=64, expand=8,
                                    segment_iters=8, policy=_policy())
    finally:
        resilience._inject_fault = None
    retries = [a for a in r["attempts"] if a.get("event") == "transient"]
    ok = r["valid"] == base["valid"] and len(retries) == 2
    return ok, f"verdict {r['valid']}, {len(retries)} jittered retries"


def scenario_hung_client(seed):
    from jepsen_tpu import core, generator as gen
    from jepsen_tpu.testing import AtomClient, SharedRegister, atom_test

    lock = threading.Lock()
    state = {"n": 0, "hung": 0}

    class HangingClient(AtomClient):
        def open(self, test, node):
            return HangingClient(self.register)

        def invoke(self, test, op):
            with lock:
                state["n"] += 1
                me = state["n"]
            if me == 3 and not state["hung"]:
                state["hung"] = 1
                threading.Event().wait(60)
            return super().invoke(test, op)

    reg = SharedRegister()
    t = atom_test(reg)
    t["client"] = HangingClient(reg)
    t["op-timeout"] = 0.3
    t["store-dir"] = None
    t["generator"] = gen.clients(
        gen.stagger(0.01, gen.limit(80, gen.cas_gen())))
    t0 = time.time()
    t = core.run(t)
    wall = time.time() - t0
    infos = [o for o in t["history"]
             if o.is_info and o.process != "nemesis"
             and o.error and "OpTimeout" in str(o.error)]
    ok = bool(infos) and state["hung"] == 1 and wall < 30
    return ok, (f"run completed in {wall:.1f}s with "
                f"{len(infos)} op-timeout info op(s)")


def _kill_kvnodes(ports):
    """Reap kvnode daemons a SIGKILLed run never tore down: match this
    run's ports in /proc cmdlines, CONT (a paused daemon ignores KILL
    delivery ordering otherwise) then KILL."""
    pats = [f"--port {p}" for p in ports]
    for pid_dir in glob.glob("/proc/[0-9]*"):
        try:
            with open(os.path.join(pid_dir, "cmdline"), "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode()
        except OSError:
            continue
        if "kvnode.py" in cmd and any(p in cmd for p in pats):
            pid = int(os.path.basename(pid_dir))
            for sig in (signal.SIGCONT, signal.SIGKILL):
                try:
                    os.kill(pid, sig)
                except OSError:
                    pass


def scenario_kill9_recover(seed):
    """SIGKILL a REAL localkv run mid-workload; assert `recover` turns
    its write-ahead journal into a checkable history + verdict."""
    import contextlib
    import io
    import tempfile

    from jepsen_tpu import cli, store

    root = tempfile.mkdtemp(prefix="jepsen-chaos-kill9-")
    run_dir = os.path.join(root, "local-kv", "run")
    ports_file = os.path.join(root, "ports.json")
    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import core\n"
        "from jepsen_tpu.suites.localkv import localkv_test\n"
        "test = localkv_test({'time-limit': 60, 'nemesis-period': 3})\n"
        f"test['store-dir'] = {run_dir!r}\n"
        f"json.dump(test['localkv-ports'], open({ports_file!r}, 'w'))\n"
        "core.run(test)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    wal = os.path.join(run_dir, "history.wal")
    deadline = time.time() + 90
    lines = 0
    try:
        # wait for the workload phase: the WAL grows as ops land
        while time.time() < deadline:
            if os.path.exists(wal):
                with open(wal, "rb") as f:
                    lines = sum(1 for _ in f)
                if lines >= 40:
                    break
            if proc.poll() is not None:
                return False, (f"child exited rc={proc.returncode} "
                               f"before the kill (wal lines={lines})")
            time.sleep(0.2)
        else:
            return False, f"workload never reached 40 WAL ops ({lines})"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        try:
            with open(ports_file) as f:
                _kill_kvnodes(json.load(f))
        except OSError:
            pass

    if store.run_status(run_dir) != "dead" or \
            run_dir not in store.dead_runs(root):
        return False, (f"dead-run scan missed the killed run "
                       f"(status={store.run_status(run_dir)!r})")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(cli.default_commands(),
                     ["recover", "--store-root", root])
    out = buf.getvalue().strip()
    if "# recovery:" not in out:
        return False, f"no '# recovery:' summary in output: {out!r}"
    results = os.path.join(run_dir, "results.json")
    if not os.path.exists(results):
        return False, "recover wrote no results.json"
    with open(results) as f:
        valid = json.load(f).get("valid")
    # safe-mode localkv is linearizable by construction: the recovered
    # partial history must check valid, and recover must exit 0
    ok = rc == 0 and valid is True and \
        store.run_status(run_dir) == "recovered"
    summary = [ln for ln in out.splitlines()
               if ln.startswith("# recovery:")][0]
    return ok, (f"rc={rc} valid={valid} "
                f"status={store.run_status(run_dir)}; {summary}")


def scenario_malformed_history(seed):
    """Corrupt a REAL localkv history (dangling invoke, process reuse,
    bad op type); assert the pre-search lint gate rejects each with the
    right rule id before any jit compilation occurs."""
    from jepsen_tpu import core
    from jepsen_tpu.analysis.history_lint import MalformedHistoryError
    from jepsen_tpu.checker import tpu
    from jepsen_tpu.history import History
    from jepsen_tpu.suites.localkv import localkv_test

    # A real localkv run (real daemons, real sockets), store disabled —
    # only the history matters here.
    test = localkv_test({"time-limit": 6, "nemesis-period": 2})
    test["store-dir"] = None
    test = core.run(test)
    h = test["history"]
    if test["results"].get("valid") is not True:
        return False, (f"clean localkv run should validate, got "
                       f"{test['results'].get('valid')}")

    def corrupt_dangling(ops):
        """Drop an ok completion whose process later invokes a
        DIFFERENT op — the abandoned invoke is mid-stream dangling.
        (An identical next invoke would read as process reuse, which
        is the next corruption's job.)"""
        for i, o in enumerate(ops):
            if o.type != "ok":
                continue
            nxt = next((p for p in ops[i + 1:]
                        if p.process == o.process
                        and p.type == "invoke"), None)
            if nxt is not None and nxt.f != o.f:
                return History.of(ops[:i] + ops[i + 1:])
        return None

    def corrupt_reuse(ops):
        """Duplicate an invoke in place: the process is reused before
        its open op completes."""
        for i, o in enumerate(ops):
            if o.type == "invoke":
                dup = o.replace(index=-1)
                return History.of(ops[:i + 1] + [dup] + ops[i + 1:])
        return None

    def corrupt_type(ops):
        """Flip one completion's type to a value outside the op
        algebra."""
        for i, o in enumerate(ops):
            if o.type == "ok":
                return History.of(
                    ops[:i] + [o.replace(type="okk")] + ops[i + 1:])
        return None

    corruptions = (("dangling invoke", corrupt_dangling,
                    "HIST-DANGLING-INVOKE"),
                   ("process reuse", corrupt_reuse, "HIST-PROC-REUSE"),
                   ("bad op type", corrupt_type, "HIST-OP-TYPE"))

    # Any jit-factory call on a rejected history is a scenario failure.
    compiled = []
    real = (tpu._jit_single, tpu._jit_segment, tpu._jit_batch)

    def _traced(name):
        def f(*a, **k):
            compiled.append(name)
            raise AssertionError(f"{name} invoked for a malformed "
                                 f"history")
        return f

    details = []
    ok = True
    tpu._jit_single = _traced("_jit_single")
    tpu._jit_segment = _traced("_jit_segment")
    tpu._jit_batch = _traced("_jit_batch")
    try:
        for label, fn, want_rule in corruptions:
            bad = fn(list(h))
            if bad is None:
                ok = False
                details.append(f"{label}: no corruptible op found")
                continue
            try:
                tpu.check_history_tpu(bad, test["model"])
                ok = False
                details.append(f"{label}: NOT rejected")
            except MalformedHistoryError as e:
                if want_rule in str(e):
                    details.append(f"{label}->{want_rule}")
                else:
                    ok = False
                    details.append(f"{label}: wrong rule in {e}")
    finally:
        (tpu._jit_single, tpu._jit_segment, tpu._jit_batch) = real
    if compiled:
        ok = False
        details.append(f"jit fired: {compiled}")
    return ok, ("gate rejected " + ", ".join(details)
                + f"; clean run valid over {len(h)} ops")


def scenario_trace_integrity(seed):
    """SIGKILL a TRACED localkv run mid-workload; assert the streamed
    span trace survives the crash: trace.jsonl reads tail-tolerantly
    (at most the in-flight line is torn), and `recover` emits a
    `# trace:` span-count summary next to `# lint:`/`# recovery:`."""
    import contextlib
    import io
    import tempfile

    from jepsen_tpu import cli
    from jepsen_tpu.obs import trace as trace_ns

    root = tempfile.mkdtemp(prefix="jepsen-chaos-traceint-")
    run_dir = os.path.join(root, "local-kv", "run")
    ports_file = os.path.join(root, "ports.json")
    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import core\n"
        "from jepsen_tpu.suites.localkv import localkv_test\n"
        "test = localkv_test({'time-limit': 60, 'nemesis-period': 3})\n"
        f"test['store-dir'] = {run_dir!r}\n"
        f"json.dump(test['localkv-ports'], open({ports_file!r}, 'w'))\n"
        "core.run(test)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JTPU_TRACE="1")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    trace_path = os.path.join(run_dir, trace_ns.TRACE_NAME)
    wal = os.path.join(run_dir, "history.wal")
    deadline = time.time() + 90
    spans = wal_lines = 0
    try:
        # wait for a mid-workload state: ops in the WAL AND spans in
        # the trace (both stream as they happen)
        while time.time() < deadline:
            if os.path.exists(wal) and os.path.exists(trace_path):
                with open(wal, "rb") as f:
                    wal_lines = sum(1 for _ in f)
                with open(trace_path, "rb") as f:
                    spans = sum(1 for _ in f)
                if wal_lines >= 40 and spans >= 10:
                    break
            if proc.poll() is not None:
                return False, (f"child exited rc={proc.returncode} "
                               f"before the kill (wal={wal_lines}, "
                               f"spans={spans})")
            time.sleep(0.2)
        else:
            return False, (f"workload never produced enough telemetry "
                           f"(wal={wal_lines}, spans={spans})")
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        try:
            with open(ports_file) as f:
                _kill_kvnodes(json.load(f))
        except OSError:
            pass

    # tail-tolerant read of the crashed run's trace: must not raise,
    # and at most one torn line (the span in flight at the kill)
    records, stats = trace_ns.read_trace(trace_path)
    if not records or stats["corrupt"] or stats["torn"] > 1:
        return False, f"trace read after SIGKILL: {stats}"
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(cli.default_commands(),
                     ["recover", "--store-root", root, "--no-analyze"])
    out = buf.getvalue()
    has_recovery = "# recovery:" in out
    has_lint = "# lint:" in out
    trace_lines = [ln for ln in out.splitlines()
                   if ln.startswith("# trace:")]
    ok = (rc == 0 and has_recovery and has_lint and bool(trace_lines)
          and f"{stats['spans']} span(s)" in trace_lines[0])
    return ok, (f"rc={rc} {stats['spans']} span(s) "
                f"({stats['torn']} torn) survived the SIGKILL; "
                f"recover said: {trace_lines[:1]!r}")


def scenario_watched_kill(seed):
    """SIGKILL a WATCHED localkv run mid-workload; assert the live
    observability surfaces survive the crash: the `/live/<test>/<ts>`
    endpoint answers with the dead run's state (never a 500), the
    `watch` CLI renders a graceful status line, and `recover` still
    turns the WAL into a verdict."""
    import contextlib
    import io
    import json as _json
    import tempfile
    import urllib.request

    from jepsen_tpu import cli, store, web

    root = tempfile.mkdtemp(prefix="jepsen-chaos-watched-")
    run_dir = os.path.join(root, "local-kv", "run")
    ports_file = os.path.join(root, "ports.json")
    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import core\n"
        "from jepsen_tpu.suites.localkv import localkv_test\n"
        "test = localkv_test({'time-limit': 60, 'nemesis-period': 3})\n"
        f"test['store-dir'] = {run_dir!r}\n"
        f"json.dump(test['localkv-ports'], open({ports_file!r}, 'w'))\n"
        "core.run(test)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JTPU_TRACE="1")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    wal = os.path.join(run_dir, "history.wal")
    deadline = time.time() + 90
    lines = 0
    try:
        while time.time() < deadline:
            if os.path.exists(wal):
                with open(wal, "rb") as f:
                    lines = sum(1 for _ in f)
                if lines >= 40:
                    break
            if proc.poll() is not None:
                return False, (f"child exited rc={proc.returncode} "
                               f"before the kill (wal lines={lines})")
            time.sleep(0.2)
        else:
            return False, f"workload never reached 40 WAL ops ({lines})"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        try:
            with open(ports_file) as f:
                _kill_kvnodes(json.load(f))
        except OSError:
            pass

    if store.run_status(run_dir) != "dead":
        return False, (f"killed run not detected as dead "
                       f"(status={store.run_status(run_dir)!r})")
    # live endpoint on the dead run: must answer JSON, never 500 (the
    # kill landed mid-workload, before any search segment — progress
    # is legitimately absent)
    server = web.serve_background(root=root)
    try:
        url = (f"http://127.0.0.1:{server.server_port}"
               f"/live/local-kv/run")
        with urllib.request.urlopen(url, timeout=10) as r:
            live_ok = r.status == 200
            doc = _json.load(r)
        live_ok = live_ok and doc.get("state") == "dead" \
            and "progress" in doc
    except Exception as e:  # noqa: BLE001 — an erroring endpoint fails
        return False, f"/live endpoint died on the killed run: {e!r}"
    finally:
        server.shutdown()
    # watch CLI on the dead run: one graceful line, exit 0
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        watch_rc = cli.run(cli.default_commands(),
                           ["watch", "--store", run_dir, "--once"])
    watch_out = buf.getvalue()
    # and the run still recovers to a verdict, exactly like kill9
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(cli.default_commands(),
                     ["recover", "--store-root", root])
    out = buf.getvalue()
    recovered = (rc == 0 and "# recovery:" in out
                 and store.run_status(run_dir) == "recovered")
    ok = live_ok and watch_rc == 0 and "# watch:" in watch_out \
        and recovered
    return ok, (f"/live answered state=dead progress="
                f"{doc.get('progress') is not None}; watch rc="
                f"{watch_rc}; recover rc={rc} "
                f"status={store.run_status(run_dir)}")


def scenario_explain_kill(seed):
    """SIGKILL a localkv run mid-search; assert the verdict-explain
    surfaces stay torn-tolerant: a partial (or absent) searchstats.json
    never breaks them — `recover` turns the WAL back into a verdict,
    `jtpu explain` renders a report from whatever survived, and the web
    `/explain/<test>/<ts>` page answers 200, never a 500."""
    import contextlib
    import io
    import json as _json
    import tempfile
    import urllib.request

    from jepsen_tpu import cli, store, web

    root = tempfile.mkdtemp(prefix="jepsen-chaos-explain-")
    run_dir = os.path.join(root, "local-kv", "run")
    ports_file = os.path.join(root, "ports.json")
    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import core\n"
        "from jepsen_tpu.suites.localkv import localkv_test\n"
        "test = localkv_test({'time-limit': 60, 'nemesis-period': 3})\n"
        f"test['store-dir'] = {run_dir!r}\n"
        f"json.dump(test['localkv-ports'], open({ports_file!r}, 'w'))\n"
        "core.run(test)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JTPU_TRACE="1")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    wal = os.path.join(run_dir, "history.wal")
    deadline = time.time() + 90
    lines = 0
    try:
        while time.time() < deadline:
            if os.path.exists(wal):
                with open(wal, "rb") as f:
                    lines = sum(1 for _ in f)
                if lines >= 40:
                    break
            if proc.poll() is not None:
                return False, (f"child exited rc={proc.returncode} "
                               f"before the kill (wal lines={lines})")
            time.sleep(0.2)
        else:
            return False, f"workload never reached 40 WAL ops ({lines})"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        try:
            with open(ports_file) as f:
                _kill_kvnodes(json.load(f))
        except OSError:
            pass

    # simulate the worst tear: a half-written searchstats.json (the
    # kill may have landed mid-os.replace on some filesystems)
    torn = os.path.join(run_dir, "searchstats.json")
    with open(torn, "w") as f:
        f.write('{"ts": 1, "levels": [[3, 1')
    # recover rebuilds the history and re-checks to a verdict
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(cli.default_commands(),
                     ["recover", "--store-root", root])
    if rc != 0 or store.run_status(run_dir) != "recovered":
        return False, (f"recover rc={rc} "
                       f"status={store.run_status(run_dir)!r}")
    # jtpu explain renders a report from the recovered artifacts,
    # shrugging off the torn searchstats.json
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        exp_rc = cli.run(cli.default_commands(),
                         ["explain", "--store", run_dir])
    exp_out = buf.getvalue()
    if exp_rc not in (0, 1) or "# explain:" not in exp_out:
        return False, (f"explain rc={exp_rc}; "
                       f"output: {exp_out[:200]!r}")
    # and the web page answers 200, never a 500
    server = web.serve_background(root=root)
    try:
        url = (f"http://127.0.0.1:{server.server_port}"
               f"/explain/local-kv/run")
        with urllib.request.urlopen(url, timeout=10) as r:
            page_ok = r.status == 200
            page = r.read().decode()
    except Exception as e:  # noqa: BLE001 — an erroring page fails
        return False, f"/explain page died on the torn run: {e!r}"
    finally:
        server.shutdown()
    ok = page_ok and "# explain:" in page
    return ok, (f"recover rc={rc}; explain rc={exp_rc} "
                f"({len(exp_out.splitlines())} line(s)); /explain "
                f"status={'200' if page_ok else 'not 200'} with torn "
                f"searchstats.json")


def scenario_prof_kill(seed):
    """SIGKILL a ``--profile`` localkv run MID-CAPTURE (the device
    profiler is recording when the kill lands); assert the partial
    capture is tail-tolerantly readable (read_profile never raises —
    a killed capture may have written nothing, or a torn file),
    `recover` still renders a verdict from the WAL, and `trace export`
    degrades gracefully to valid Chrome JSON."""
    import contextlib
    import io
    import tempfile

    from jepsen_tpu import cli, store
    from jepsen_tpu.obs import profiler

    root = tempfile.mkdtemp(prefix="jepsen-chaos-profkill-")
    run_dir = os.path.join(root, "local-kv", "run")
    ports_file = os.path.join(root, "ports.json")
    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import core\n"
        "from jepsen_tpu.suites.localkv import localkv_test\n"
        "test = localkv_test({'time-limit': 8, 'nemesis-period': 3,\n"
        "                     'backend': 'tpu'})\n"
        f"test['store-dir'] = {run_dir!r}\n"
        f"json.dump(test['localkv-ports'], open({ports_file!r}, 'w'))\n"
        "core.run(test)\n")
    # JTPU_PROF=1 arms the capture; 1-iteration segments stretch the
    # checker phase over hundreds of device calls so the SIGKILL
    # reliably lands while the profiler is recording.
    env = dict(os.environ, JAX_PLATFORMS="cpu", JTPU_TRACE="1",
               JTPU_PROF="1", JTPU_SEGMENT_ITERS="1")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    prof_dir = profiler.profile_dir(run_dir)
    deadline = time.time() + 120
    try:
        # wait for the capture itself: the profile dir is created at
        # jax.profiler.start_trace, i.e. the search is being profiled
        while time.time() < deadline:
            if os.path.isdir(prof_dir):
                break
            if proc.poll() is not None:
                return False, (f"child exited rc={proc.returncode} "
                               f"before any capture started")
            time.sleep(0.05)
        else:
            return False, "capture never started within 120s"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        try:
            with open(ports_file) as f:
                _kill_kvnodes(json.load(f))
        except OSError:
            pass

    # (1) the partial capture reads tail-tolerantly: whatever the kill
    # left behind (nothing, xplane-only, or a torn trace.json.gz) must
    # answer with records + stats, never an exception
    records, pstats = profiler.read_profile(run_dir)
    # (2) recover still renders a verdict from the WAL
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(cli.default_commands(),
                     ["recover", "--store-root", root])
    out = buf.getvalue()
    recovered = (rc == 0 and "# recovery:" in out
                 and store.run_status(run_dir) == "recovered")
    # (3) trace export degrades gracefully: rc 0, valid Chrome JSON
    export = os.path.join(root, "chrome.json")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        export_rc = cli.run(cli.default_commands(),
                            ["trace", "export", "--store", run_dir,
                             "-o", export])
    export_ok = False
    if export_rc == 0 and os.path.exists(export):
        try:
            with open(export) as f:
                doc = json.load(f)
            evs = doc.get("traceEvents")
            export_ok = isinstance(evs, list) and len(evs) > 0 and \
                all("name" in e and "ph" in e for e in evs)
        except ValueError:
            export_ok = False
    ok = recovered and export_ok
    return ok, (f"capture killed mid-flight: {pstats['files']} trace "
                f"file(s), {len(records)} device record(s), "
                f"{pstats['errors']} unreadable; recover rc={rc} "
                f"status={store.run_status(run_dir)}; export "
                f"rc={export_rc} valid-chrome={export_ok}")


def scenario_plan_rejects(seed):
    """Drive a REAL localkv history into the pre-search plan gate with
    (1) an oversized explicit capacity under a tiny byte budget and
    (2) a mesh axis that divides neither capacity nor expand; assert
    each is rejected with the right PLAN-* rule id and that the jit
    factories were never invoked. The same history then checks valid
    with the oversized knobs removed."""
    import types

    from jepsen_tpu import core
    from jepsen_tpu.analysis.plan_lint import PlanRejectedError
    from jepsen_tpu.checker import tpu
    from jepsen_tpu.suites.localkv import localkv_test

    test = localkv_test({"time-limit": 6, "nemesis-period": 2})
    test["store-dir"] = None
    test = core.run(test)
    h = test["history"]
    if test["results"].get("valid") is not True:
        return False, (f"clean localkv run should validate, got "
                       f"{test['results'].get('valid')}")

    compiled = []
    real = (tpu._jit_single, tpu._jit_segment, tpu._jit_batch)

    def _traced(name):
        def f(*a, **k):
            compiled.append(name)
            raise AssertionError(f"{name} invoked for a rejected plan")
        return f

    details = []
    ok = True
    tpu._jit_single = _traced("_jit_single")
    tpu._jit_segment = _traced("_jit_segment")
    tpu._jit_batch = _traced("_jit_batch")
    os.environ["JTPU_PLAN_BYTES_LIMIT"] = "200000"
    try:
        # (1) oversized capacity vs the byte budget -> PLAN-OOM
        try:
            tpu.check_history_tpu(h, test["model"], capacity=16384,
                                  window=32)
            ok = False
            details.append("oversized capacity NOT rejected")
        except PlanRejectedError as e:
            if "PLAN-OOM" in str(e):
                details.append("capacity-16384->PLAN-OOM")
            else:
                ok = False
                details.append(f"capacity: wrong rule in {e}")
        # (2) a mesh axis dividing neither capacity nor expand. The
        # gate fires on the axis size alone — before jax.set_mesh —
        # so a shape-only stand-in exercises exactly the gated path.
        mesh = types.SimpleNamespace(shape={tpu.POOL_AXIS: 3})
        try:
            tpu.check_history_sharded(h, test["model"], mesh,
                                      capacity=128, expand=10)
            ok = False
            details.append("non-dividing mesh NOT rejected")
        except PlanRejectedError as e:
            if "PLAN-SHARD-INDIVISIBLE" in str(e):
                details.append("mesh-3->PLAN-SHARD-INDIVISIBLE")
            else:
                ok = False
                details.append(f"mesh: wrong rule in {e}")
    finally:
        (tpu._jit_single, tpu._jit_segment, tpu._jit_batch) = real
        os.environ.pop("JTPU_PLAN_BYTES_LIMIT", None)
    if compiled:
        ok = False
        details.append(f"jit fired: {compiled}")
    # (3) same history, sane knobs: the gate admits and the verdict
    # still renders (the gate must reject configurations, not work)
    r = tpu.check_history_tpu(h, test["model"])
    if r["valid"] is not True or "plan" not in r:
        ok = False
        details.append(f"clean config valid={r['valid']} "
                       f"plan={'plan' in r}")
    else:
        details.append(f"clean config valid via {r['plan']['selected']}")
    return ok, ("; ".join(details) + f" over {len(h)} ops")


def scenario_fleet_host_kill(seed):
    """SIGKILL one worker of a 2-process elastic-fleet search (the
    CPU-simulated DCN mesh: each host is a real OS process running
    shard segments over a file protocol) mid-rung. The survivor must
    detect the loss (dead pid / stale heartbeat), re-mesh at the merge
    barrier with a ``remesh-to-1-hosts`` trail event, and finish with
    a verdict identical to the uninterrupted single-host baseline and
    the CPU oracle."""
    import signal
    import tempfile

    from jepsen_tpu import fleet

    p, kernel = _packed(seed)
    base = supervised_check_packed(p, kernel, segment_iters=4)
    oracle = check_packed(p, kernel)
    if base["valid"] != oracle["valid"]:
        return False, "single-host baseline disagrees with the oracle"
    d = tempfile.mkdtemp(prefix="jtpu-fleet-")
    hosts = [fleet.ProcHost("w0", os.path.join(d, "w0")),
             fleet.ProcHost("w1", os.path.join(d, "w1"))]
    killed = []

    def chaos(round_idx, fl):
        if round_idx == 2 and fl.hosts[1].state == "live":
            os.kill(fl.hosts[1].pid, signal.SIGKILL)
            killed.append(fl.hosts[1].pid)

    # SIGKILL detection rides the pid poll (instant), not heartbeat
    # staleness, so the default JTPU_FLEET_DEAD_S stays — a loaded CI
    # box must not misread a slow-beating survivor as a second death
    out = fleet.check_packed_fleet(p, kernel, hosts=hosts,
                                   segment_iters=2, on_round=chaos)
    if not killed:
        return False, "search finished before the kill round"
    evs = [e.get("outcome") for e in out.get("attempts", [])]
    details = []
    ok = True
    if out.get("valid") != base["valid"]:
        ok = False
        details.append(f"verdict {out.get('valid')!r} != baseline "
                       f"{base['valid']!r}")
    else:
        details.append(f"verdict {out['valid']} == single-host "
                       f"baseline == oracle")
    if "remesh-to-1-hosts" not in evs:
        ok = False
        details.append(f"no remesh-to-1-hosts event in {evs}")
    else:
        details.append("remesh-to-1-hosts after SIGKILL")
    lost = (out.get("fleet") or {}).get("hosts-lost")
    if lost != 1:
        ok = False
        details.append(f"hosts-lost={lost}, want 1")
    return ok, "; ".join(details)


def scenario_straggler_host(seed):
    """Deliberately slow ONE worker of a 2-process elastic-fleet search
    (``JTPU_CHAOS_SLOW_HOST`` stalls it before every shard segment —
    verdict-neutral added latency). The straggler observatory must flag
    exactly that host — and only it — within 3 merge rounds in which
    it actually ran a segment (an empty contiguous shard is not
    dispatched, and an idle host cannot be observed;
    ``straggler-flagged`` trail event), the flag must force a
    ``steal-rebalance`` re-deal without waiting out the row-imbalance
    streak, and the verdict must match the single-host baseline and the
    CPU oracle. A second serve-side leg drives a burst through a
    fleet-backed daemon with the same slowed worker and proves trace
    search (``jtpu trace find --host``) resolves the requests that ran
    on it, with every verdict offline-identical
    (doc/observability.md, "Fleet federation")."""
    import tempfile
    import urllib.request

    from jepsen_tpu import fleet
    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History
    from jepsen_tpu.obs import federation as obs_federation

    p, kernel = _packed(seed)
    base = supervised_check_packed(p, kernel, segment_iters=1)
    oracle = check_packed(p, kernel)
    if base["valid"] != oracle["valid"]:
        return False, "single-host baseline disagrees with the oracle"
    details = []

    # leg 1: the elastic-fleet search — flag within 3 rounds, steal
    d = tempfile.mkdtemp(prefix="jtpu-straggler-")
    hosts = [fleet.ProcHost("w0", os.path.join(d, "w0")),
             fleet.ProcHost("w1", os.path.join(d, "w1"))]
    os.environ["JTPU_CHAOS_SLOW_HOST"] = "w1:2.0"
    try:
        out = fleet.check_packed_fleet(p, kernel, hosts=hosts,
                                       segment_iters=1)
    finally:
        os.environ.pop("JTPU_CHAOS_SLOW_HOST", None)
    if out.get("valid") != base["valid"]:
        return False, (f"verdict {out.get('valid')!r} != baseline "
                       f"{base['valid']!r}")
    details.append(f"verdict {out['valid']} == single-host baseline "
                   f"== oracle")
    evs = out.get("attempts", [])
    flags = [e for e in evs if e.get("event") == "straggler-flagged"]
    flagged_hosts = {e.get("host") for e in flags}
    if "w1" not in flagged_hosts:
        return False, (f"slowed host w1 never flagged (events "
                       f"{[e.get('event') for e in evs]})")
    if flagged_hosts != {"w1"}:
        return False, (f"flagged {sorted(flagged_hosts)}, want the "
                       f"slowed host only")
    # the 3-round flag budget counts rounds the straggler actually
    # RAN: a shard whose contiguous slice holds no live rows is not
    # dispatched at all, and an idle host cannot be observed — its
    # dispatched rounds are in its own segment spans
    w1_rounds = []
    try:
        with open(os.path.join(d, "w1", "trace.jsonl"),
                  errors="replace") as f:
            for line in f:
                try:
                    sp = json.loads(line)
                except ValueError:
                    continue
                if sp.get("name") == "checker.segment" \
                        and sp.get("round") is not None:
                    w1_rounds.append(int(sp["round"]))
    except OSError:
        pass
    w1_rounds = sorted(set(w1_rounds))
    if len(w1_rounds) < 3:
        return False, (f"w1 ran only {len(w1_rounds)} segment "
                       f"round(s) — too few to flag")
    first = min(e.get("round", 10 ** 9) for e in flags)
    if first > w1_rounds[2]:
        return False, (f"w1 flagged at round {first}, want by its 3rd "
                       f"dispatched segment (rounds {w1_rounds[:4]})")
    nth = w1_rounds.index(first) + 1 if first in w1_rounds else "?"
    details.append(f"w1 (and only w1) flagged at round {first} — "
                   f"dispatched segment #{nth} of its "
                   f"{len(w1_rounds)}")
    if not any(e.get("outcome") == "steal-rebalance" for e in evs):
        return False, (f"no steal-rebalance after the flag "
                       f"(events {[e.get('event') for e in evs]})")
    details.append("flag forced a steal-rebalance re-deal")

    # leg 2: the serve plane — trace search attributes the burst's
    # requests to the slowed worker, verdicts stay offline-identical
    root = tempfile.mkdtemp(prefix="jepsen-chaos-straggler-")
    all_ops = [[o.to_dict() for o in
                simulate_register_history(40, n_procs=3, n_vals=3,
                                          seed=seed + i)]
               for i in range(3)]
    offline = [check_safe(linearizable(CASRegister(), backend="tpu"),
                          {"name": "chaos-straggler-offline"},
                          History.of(o)) for o in all_ops]
    os.environ["JTPU_SEGMENT_ITERS"] = "2"
    os.environ["JTPU_CHAOS_SLOW_HOST"] = "fleet-host-1:0.15"
    cfg = serve_ns.ServeConfig(root=os.path.join(root, "serve"),
                               backend="tpu", workers=1,
                               batch_max=8, batch_wait_ms=1000.0,
                               fleet_hosts=2, fleet_backend="proc")
    daemon, server = serve_ns.run_daemon(
        cfg, host="127.0.0.1", port=0, store_root=root)
    port = server.server_port
    try:
        rids = []
        for i, o in enumerate(all_ops):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/check",
                data=json.dumps({"tenant": "abc"[i],
                                 "model": "cas-register",
                                 "history": o}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                rids.append(json.load(r)["id"])
        deadline = time.time() + 120
        docs = {}
        while time.time() < deadline and len(docs) < len(rids):
            for rid in rids:
                if rid in docs:
                    continue
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/check/{rid}",
                        timeout=10) as r:
                    doc = json.load(r)
                if doc.get("state") == "done":
                    docs[rid] = doc
            time.sleep(0.05)
        if len(docs) != len(rids):
            return False, (f"only {len(docs)}/{len(rids)} serve "
                           f"requests finished")
        for i, rid in enumerate(rids):
            got = docs[rid]["result"].get("valid")
            if got != offline[i].get("valid"):
                return False, (f"served verdict {got!r} != offline "
                               f"{offline[i].get('valid')!r}")
    finally:
        os.environ.pop("JTPU_SEGMENT_ITERS", None)
        os.environ.pop("JTPU_CHAOS_SLOW_HOST", None)
        server.shutdown()
        daemon.stop()
    rows = obs_federation.trace_find(cfg.root, host="fleet-host-1")
    found = {r["id"] for r in rows}
    if not found & set(rids):
        return False, (f"trace find --host fleet-host-1 resolved "
                       f"{sorted(found)}, none of the burst")
    details.append(f"trace find attributed {len(found & set(rids))} "
                   f"burst request(s) to the slowed serve worker; "
                   f"all serve verdicts == offline")
    return True, "; ".join(details)


def scenario_serve_kill(seed):
    """SIGKILL the check daemon (`jtpu serve`) with one request
    IN-FLIGHT and one QUEUED. A restarted daemon must replay its
    request journal (serve.wal), re-run both requests, and render
    verdicts identical to the offline analyze path — the serve layer's
    crash-safety proof (doc/serve.md)."""
    import tempfile
    import urllib.request

    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History
    from jepsen_tpu.testing import simulate_register_history

    root = tempfile.mkdtemp(prefix="jepsen-chaos-servekill-")
    serve_dir = os.path.join(root, "serve")
    port_file = os.path.join(root, "port.json")
    # req1: dense enough that a cold child process is still checking it
    # when the kill lands; req2: small, stays queued behind it
    h1 = simulate_register_history(300, n_procs=5, n_vals=4, seed=seed)
    h2 = simulate_register_history(40, n_procs=3, n_vals=3,
                                   seed=seed + 1)
    ops1 = [o.to_dict() for o in h1]
    ops2 = [o.to_dict() for o in h2]

    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import serve as S\n"
        f"cfg = S.ServeConfig(root={serve_dir!r}, backend='tpu', "
        "workers=1)\n"
        f"d, srv = S.run_daemon(cfg, host='127.0.0.1', port=0, "
        f"store_root={root!r})\n"
        f"json.dump({{'port': srv.server_port}}, "
        f"open({port_file!r}, 'w'))\n"
        "d.drained.wait()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)

    def post(port, doc):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/check",
            data=json.dumps(doc).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    def get_state(port, rid):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/check/{rid}", timeout=10) as r:
            return json.load(r)["state"]

    try:
        deadline = time.time() + 60
        port = None
        while time.time() < deadline:
            if os.path.exists(port_file):
                try:
                    with open(port_file) as f:
                        port = json.load(f)["port"]
                    break
                except (OSError, ValueError):
                    pass
            if proc.poll() is not None:
                return False, f"daemon exited rc={proc.returncode} at boot"
            time.sleep(0.1)
        if port is None:
            return False, "daemon never published its port"
        r1 = post(port, {"tenant": "a", "model": "cas-register",
                         "history": ops1})
        r2 = post(port, {"tenant": "b", "model": "cas-register",
                         "history": ops2})
        # wait for the exact crash window: req1 in flight, req2 queued
        while time.time() < deadline:
            s1 = get_state(port, r1["id"])
            if s1 == "done":
                return False, ("req1 finished before the kill — make "
                               "it denser")
            if s1 == "running" and get_state(port, r2["id"]) == "queued":
                break
            time.sleep(0.02)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()

    # restart (in-process incarnation on the same journal)
    d2 = serve_ns.CheckDaemon(
        serve_ns.ServeConfig(root=serve_dir, backend="tpu", workers=1))
    d2.start()
    details = []
    ok = True
    if d2.replay_stats.get("requeued") != 2:
        d2.stop()
        return False, (f"replay requeued "
                       f"{d2.replay_stats.get('requeued')}, want 2 "
                       f"(stats {d2.replay_stats})")
    details.append("replayed 2 journaled request(s) after SIGKILL")
    with d2._lock:
        rids = list(d2._by_id)
    deadline = time.time() + 120
    docs = {}
    for rid in rids:
        while time.time() < deadline:
            doc = d2.status(rid)
            if doc and doc["state"] == "done":
                docs[rid] = doc
                break
            time.sleep(0.05)
    d2.drain(timeout_s=10)
    d2.stop()
    if len(docs) != 2:
        return False, f"re-checked {len(docs)}/2 replayed requests"
    # both verdicts must match the offline analyze path
    for doc, ops in zip(
            (docs[r] for r in sorted(docs, key=lambda x: docs[x][
                "tenant"])),
            (ops1, ops2)):
        offline = check_safe(
            linearizable(CASRegister(), backend="tpu"),
            {"name": "chaos-serve-offline"}, History.of(ops))
        got = doc["result"].get("valid")
        if got != offline.get("valid"):
            ok = False
            details.append(f"tenant {doc['tenant']}: served {got!r} != "
                           f"offline {offline.get('valid')!r}")
        else:
            details.append(f"tenant {doc['tenant']}: verdict {got} == "
                           f"offline")
    return ok, "; ".join(details)


def scenario_trace_request_kill(seed):
    """SIGKILL the daemon mid-check on a TRACED request (admitted with
    an inbound traceparent). The restarted daemon's serve.wal replay
    must keep the ORIGINAL trace id, the re-run's spans must join the
    same trace, and the stitched single-request waterfall must still
    render — the request tracing layer's crash-safety proof
    (doc/observability.md, "Request tracing")."""
    import tempfile
    import urllib.request

    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu import web
    from jepsen_tpu.obs import fleet as obs_fleet
    from jepsen_tpu.obs import trace as trace_ns
    from jepsen_tpu.testing import simulate_register_history

    root = tempfile.mkdtemp(prefix="jepsen-chaos-tracereq-")
    serve_dir = os.path.join(root, "serve")
    port_file = os.path.join(root, "port.json")
    h1 = simulate_register_history(300, n_procs=5, n_vals=4, seed=seed)
    ops1 = [o.to_dict() for o in h1]
    trace_id = trace_ns.new_trace_id()

    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import serve as S\n"
        f"cfg = S.ServeConfig(root={serve_dir!r}, backend='tpu', "
        "workers=1)\n"
        f"d, srv = S.run_daemon(cfg, host='127.0.0.1', port=0, "
        f"store_root={root!r})\n"
        f"json.dump({{'port': srv.server_port}}, "
        f"open({port_file!r}, 'w'))\n"
        "d.drained.wait()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JTPU_TRACE="1")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)

    def post(port, doc, traceparent=None):
        hdrs = {"traceparent": traceparent} if traceparent else {}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/check",
            data=json.dumps(doc).encode(), method="POST",
            headers=hdrs)
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    def get_state(port, rid):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/check/{rid}",
                timeout=10) as r:
            return json.load(r)["state"]

    try:
        deadline = time.time() + 60
        port = None
        while time.time() < deadline:
            if os.path.exists(port_file):
                try:
                    with open(port_file) as f:
                        port = json.load(f)["port"]
                    break
                except (OSError, ValueError):
                    pass
            if proc.poll() is not None:
                return False, (f"daemon exited rc={proc.returncode} "
                               f"at boot")
            time.sleep(0.1)
        if port is None:
            return False, "daemon never published its port"
        body = post(port, {"tenant": "traced", "model": "cas-register",
                           "history": ops1},
                    traceparent=trace_ns.format_traceparent(trace_id))
        if body.get("trace") != trace_id:
            return False, (f"admission answered trace "
                           f"{body.get('trace')!r}, want the inbound "
                           f"{trace_id}")
        rid = body["id"]
        # kill in the exact window: the request is mid-check
        while time.time() < deadline:
            if get_state(port, rid) == "running":
                break
            time.sleep(0.02)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()

    # restart (in-process incarnation on the same journal)
    d2 = serve_ns.CheckDaemon(
        serve_ns.ServeConfig(root=serve_dir, backend="tpu", workers=1))
    d2.start()
    if d2.replay_stats.get("requeued") != 1:
        d2.stop()
        return False, (f"replay requeued "
                       f"{d2.replay_stats.get('requeued')}, want 1")
    with d2._lock:
        rid2 = next(iter(d2._by_id))
    deadline = time.time() + 120
    doc = None
    while time.time() < deadline:
        doc = d2.status(rid2)
        if doc and doc["state"] == "done":
            break
        time.sleep(0.05)
    resolved = d2.resolve_trace(rid2)
    d2.drain(timeout_s=10)
    d2.stop()
    if not doc or doc.get("state") != "done":
        return False, f"replayed request never finished: {doc}"
    details = []
    if doc.get("trace") != trace_id:
        return False, (f"replayed request re-minted trace "
                       f"{doc.get('trace')!r}, want the original "
                       f"{trace_id}")
    details.append("replayed request kept its original trace id")
    if resolved != trace_id:
        return False, (f"resolve_trace({rid2}) -> {resolved!r}, want "
                       f"{trace_id}")
    phases = doc["result"].get("serve", {}).get("phases", {})
    if "device_s" not in phases:
        return False, f"re-run verdict lost its phase breakdown: {doc}"
    details.append("re-run verdict carries a phase breakdown")
    # the stitched waterfall: both incarnations' spans, one trace
    stitched = obs_fleet.stitch_request(serve_dir, trace_id)
    names = {r["name"] for r in stitched["records"]}
    if not {"serve.request", "serve.verdict"} <= names:
        return False, (f"stitched waterfall incomplete after SIGKILL: "
                       f"{sorted(names)}")
    # spans are written at EXIT, so the killed incarnation's open
    # serve.request span is legitimately absent — but its sync anchor
    # (written at attach) proves it shared the file, and the re-run's
    # complete waterfall lives under the ORIGINAL trace id
    raw, _ = trace_ns.read_trace(
        os.path.join(serve_dir, trace_ns.TRACE_NAME))
    anchors = [r for r in raw if r["name"] == "trace.sync"]
    if len(anchors) < 2:
        return False, (f"{len(anchors)} trace.sync anchor(s) in "
                       f"trace.jsonl, want one per incarnation")
    details.append(f"stitched waterfall renders "
                   f"{len(stitched['records'])} span(s); both "
                   f"incarnations anchored the shared trace.jsonl")
    page = web.request_trace_html(stitched)
    if trace_id not in page or "serve.verdict" not in page:
        return False, "web waterfall page failed to render the trace"
    details.append("web waterfall renders")
    return True, "; ".join(details)


def scenario_serve_batch_poison(seed):
    """A 4-request same-bucket burst against a REAL daemon (HTTP, warm
    engine, gang scheduler on) with ONE poison member: the injected
    gang fault (`checker.tpu._GANG_FAULT`) OOMs every device call whose
    gang contains the poison request. Bisection must isolate it — the
    3 survivors answer 200 with verdicts identical to the offline
    analyze path, the poison answers 500 with an oom-class error, and
    its bucket's breaker counts EXACTLY one failure (doc/serve.md,
    "Concurrent batching")."""
    import tempfile
    import urllib.error
    import urllib.request

    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu import web
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker import tpu as tpu_ns
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History
    from jepsen_tpu.ops.encode import pack_with_init
    from jepsen_tpu.testing import simulate_register_history

    root = tempfile.mkdtemp(prefix="jepsen-chaos-servepoison-")
    # 3 survivors at one op count, the poison at another — close enough
    # to share a shape bucket (so they coalesce into one gang), distinct
    # enough that the fault hook can recognize the poison by its packed
    # row count without touching daemon internals
    surv_ops = [[o.to_dict() for o in
                 simulate_register_history(40, n_procs=3, n_vals=3,
                                           seed=seed + i)]
                for i in range(3)]
    surv_ns = {pack_with_init(History.of(o), CASRegister())[0].n
               for o in surv_ops}
    poison_ops = poison_n = None
    for s in range(seed + 9, seed + 29):
        ops = [o.to_dict() for o in
               simulate_register_history(48, n_procs=3, n_vals=3,
                                         seed=s)]
        n = pack_with_init(History.of(ops), CASRegister())[0].n
        if n not in surv_ns:
            poison_ops, poison_n = ops, n
            break
    if poison_ops is None:
        return False, "poison history not distinguishable by row count"

    offline = [check_safe(linearizable(CASRegister(), backend="tpu"),
                          {"name": "chaos-poison-offline"},
                          History.of(o)) for o in surv_ops]

    cfg = serve_ns.ServeConfig(root=os.path.join(root, "serve"),
                               backend="tpu", workers=1,
                               batch_max=8, batch_wait_ms=1000.0)
    daemon = serve_ns.CheckDaemon(cfg)
    if daemon.batcher is None:
        return False, "gang scheduler unexpectedly disabled"
    daemon.start()
    server = web.serve(host="127.0.0.1", port=0, root=root,
                       handler_cls=serve_ns.make_handler(daemon,
                                                         root=root))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_port

    def post(doc):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/check",
            data=json.dumps(doc).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    def get(rid):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/check/{rid}",
                    timeout=10) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    def gang_fault(pks):
        if any(p.n == poison_n for p in pks):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: injected gang OOM (chaos)")

    tpu_ns._GANG_FAULT = gang_fault
    details = []
    try:
        # burst: the poison first so it leads the gang, survivors land
        # inside the 1 s coalesce window behind it
        rid_p = post({"tenant": "a", "model": "cas-register",
                      "history": poison_ops})["id"]
        rid_s = [post({"tenant": "ab"[i % 2], "model": "cas-register",
                       "history": o})["id"]
                 for i, o in enumerate(surv_ops)]
        deadline = time.time() + 120
        docs = {}
        while time.time() < deadline and len(docs) < 4:
            for rid in [rid_p] + rid_s:
                if rid in docs:
                    continue
                code, doc = get(rid)
                if doc.get("state") == "done":
                    docs[rid] = (code, doc)
            time.sleep(0.05)
        if len(docs) != 4:
            return False, f"only {len(docs)}/4 requests finished"

        code, doc = docs[rid_p]
        res = doc["result"]
        gang = (res.get("serve") or {}).get("gang") or {}
        if gang.get("size", 0) < 2:
            return False, (f"no gang formed (size "
                           f"{gang.get('size')}) — burst ran serially")
        if not gang.get("poison"):
            return False, f"poison member not isolated: {res}"
        if code != 500:
            return False, f"poison answered {code}, want 500"
        if res.get("error-class") != "oom":
            return False, (f"poison error-class "
                           f"{res.get('error-class')!r}, want 'oom'")
        details.append(f"gang of {gang['size']} bisected "
                       f"{gang.get('bisections')}x; poison 500/oom")

        for i, rid in enumerate(rid_s):
            code, doc = docs[rid]
            res = doc["result"]
            g = (res.get("serve") or {}).get("gang") or {}
            if g.get("poison"):
                return False, f"survivor {i} marked poison: {res}"
            if code != 200:
                return False, f"survivor {i} answered {code}, want 200"
            if res.get("valid") != offline[i].get("valid"):
                return False, (f"survivor {i}: served "
                               f"{res.get('valid')!r} != offline "
                               f"{offline[i].get('valid')!r}")
        details.append("3 survivors: 200, verdicts == offline")

        snap = daemon.breaker.snapshot()
        fails = [r["fails"] for r in snap.values() if r["fails"]]
        if fails != [1]:
            return False, (f"breaker counted {fails or [0]} failures, "
                           f"want exactly [1] (snapshot {snap})")
        details.append("breaker counted exactly 1 failure")
        if daemon.stats["bisections"] < 1:
            return False, "no bisection recorded"
        return True, "; ".join(details)
    finally:
        tpu_ns._GANG_FAULT = None
        server.shutdown()
        daemon.stop()


def scenario_serve_fleet_host_kill(seed):
    """A multi-tenant same-bucket burst against a REAL fleet-backed
    daemon (HTTP, gang scheduler on, 2 real ``ProcHost`` worker
    processes) with one worker SIGKILLed mid-gang. The placer must
    detect the loss, re-mesh the gang's lanes onto the survivor at the
    next merge barrier, and finish: every request answers 200 with a
    verdict identical to the offline analyze path — ZERO lost verdicts,
    ZERO poison misclassification, and the breaker counts ZERO failures
    (the loss is the fleet's to absorb, not the tenants' buckets')
    (doc/serve.md, "Fleet-backed serving")."""
    import tempfile
    import urllib.error
    import urllib.request

    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu import web
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History

    root = tempfile.mkdtemp(prefix="jepsen-chaos-servefleet-")
    all_ops = [[o.to_dict() for o in
                simulate_register_history(40, n_procs=3, n_vals=3,
                                          seed=seed + i)]
               for i in range(3)]
    offline = [check_safe(linearizable(CASRegister(), backend="tpu"),
                          {"name": "chaos-fleet-offline"},
                          History.of(o)) for o in all_ops]

    # small segments force several merge barriers per gang, so the
    # SIGKILL at round 2 lands MID-gang, not after it
    os.environ["JTPU_SEGMENT_ITERS"] = "2"
    cfg = serve_ns.ServeConfig(root=os.path.join(root, "serve"),
                               backend="tpu", workers=1,
                               batch_max=8, batch_wait_ms=1000.0,
                               fleet_hosts=2, fleet_backend="proc")
    daemon = serve_ns.CheckDaemon(cfg)
    if daemon.placer is None:
        return False, "fleet placer unexpectedly disabled"
    killed = []

    def chaos(round_idx, hosts):
        if round_idx >= 2 and not killed and hosts[1].alive():
            os.kill(hosts[1].pid, signal.SIGKILL)
            killed.append(hosts[1].pid)

    daemon.placer.on_round = chaos
    daemon.start()
    server = web.serve(host="127.0.0.1", port=0, root=root,
                       handler_cls=serve_ns.make_handler(daemon,
                                                         root=root))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_port

    def post(doc):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/check",
            data=json.dumps(doc).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    def get(rid):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/check/{rid}",
                    timeout=10) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    details = []
    try:
        rids = [post({"tenant": "abc"[i], "model": "cas-register",
                      "history": o})["id"]
                for i, o in enumerate(all_ops)]
        deadline = time.time() + 120
        docs = {}
        while time.time() < deadline and len(docs) < 3:
            for rid in rids:
                if rid in docs:
                    continue
                code, doc = get(rid)
                if doc.get("state") == "done":
                    docs[rid] = (code, doc)
            time.sleep(0.05)
        if len(docs) != 3:
            return False, f"only {len(docs)}/3 requests finished"
        if not killed:
            return False, "gang finished before the kill round"
        details.append(f"SIGKILLed worker pid {killed[0]} mid-gang")

        sizes = set()
        for i, rid in enumerate(rids):
            code, doc = docs[rid]
            res = doc["result"]
            g = (res.get("serve") or {}).get("gang") or {}
            sizes.add(g.get("size", 0))
            if g.get("poison"):
                return False, (f"tenant {doc['tenant']} misclassified "
                               f"as poison: {res}")
            if code != 200:
                return False, (f"tenant {doc['tenant']} answered "
                               f"{code}, want 200")
            if res.get("valid") != offline[i].get("valid"):
                return False, (f"tenant {doc['tenant']}: served "
                               f"{res.get('valid')!r} != offline "
                               f"{offline[i].get('valid')!r}")
        if max(sizes) < 2:
            return False, (f"no gang formed (sizes {sizes}) — burst "
                           f"ran serially")
        details.append(f"gang of {max(sizes)} over 2 proc hosts: all "
                       f"verdicts == offline")

        st = daemon.placer.stats
        if st.get("host-losses", 0) < 1 or st.get("remeshes", 0) < 1:
            return False, (f"no remesh recorded after the kill "
                           f"(placer stats {st})")
        details.append(f"re-meshed to survivor ({st['remeshes']} "
                       f"remesh(es))")
        if daemon.placer.live() != 1:
            return False, (f"fleet live={daemon.placer.live()}, want 1")
        if daemon.stats["poisoned"] != 0:
            return False, (f"poisoned={daemon.stats['poisoned']}, "
                           f"want 0")
        snap = daemon.breaker.snapshot()
        fails = [r["fails"] for r in snap.values() if r["fails"]]
        if fails:
            return False, (f"breaker counted {fails} failures, want "
                           f"none (snapshot {snap})")
        details.append("breaker counted 0 failures; 0 poisoned")
        return True, "; ".join(details)
    finally:
        os.environ.pop("JTPU_SEGMENT_ITERS", None)
        server.shutdown()
        daemon.stop()


_STREAM_VERDICT_KEYS = ("valid", "levels", "max-linearized-prefix",
                        "final-states", "frontier-op")


def scenario_stream_kill(seed):
    """SIGKILL the check daemon MID-STREAM, after the online checker
    has journaled chunks and saved a partial-verdict checkpoint. A
    restarted daemon must replay the per-session WAL, resume the search
    from the checkpointed level (NEVER level 0), and — once the stream
    is sealed — render a verdict identical to the offline analyze path
    over the same ops (doc/serve.md "Streaming API",
    doc/resilience.md)."""
    import tempfile
    import urllib.request
    import zipfile

    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu import resilience as R
    from jepsen_tpu import stream as stream_mod
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History

    root = tempfile.mkdtemp(prefix="jepsen-chaos-streamkill-")
    serve_dir = os.path.join(root, "serve")
    port_file = os.path.join(root, "port.json")
    h = simulate_register_history(600, n_procs=5, n_vals=4, seed=seed)
    ops = [o.to_dict() for o in h]
    offline = check_safe(linearizable(CASRegister(), backend="tpu"),
                         {"name": "chaos-stream-offline"},
                         History.of(ops))
    chunks = [ops[i:i + 50] for i in range(0, len(ops), 50)]

    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import serve as S\n"
        f"cfg = S.ServeConfig(root={serve_dir!r}, backend='tpu', "
        "workers=1)\n"
        f"d, srv = S.run_daemon(cfg, host='127.0.0.1', port=0, "
        f"store_root={root!r})\n"
        f"json.dump({{'port': srv.server_port}}, "
        f"open({port_file!r}, 'w'))\n"
        "d.drained.wait()\n")
    # one search iteration per device call -> a checkpoint barrier
    # lands every segment, so the kill window is wide open
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JTPU_SEGMENT_ITERS="1")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)

    def post(port, path, doc):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(doc).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    cp_level = 0
    try:
        deadline = time.time() + 60
        port = None
        while time.time() < deadline:
            if os.path.exists(port_file):
                try:
                    with open(port_file) as f:
                        port = json.load(f)["port"]
                    break
                except (OSError, ValueError):
                    pass
            if proc.poll() is not None:
                return False, f"daemon exited rc={proc.returncode} at boot"
            time.sleep(0.1)
        if port is None:
            return False, "daemon never published its port"
        sid = post(port, "/stream", {"tenant": "chaos",
                                     "model": "cas-register"})["id"]
        for seq, chunk in enumerate(chunks):
            post(port, f"/stream/{sid}/ops",
                 {"seq": seq, "ops": chunk,
                  "crc": stream_mod.chunk_crc(chunk)})
        # the stream stays OPEN (no close): the online search is mid-
        # flight over the stable prefix when the SIGKILL lands. Wait
        # for a checkpoint with level > 0 so the resume has something
        # real to prove.
        cp_path = os.path.join(serve_dir, "streams", sid,
                               stream_mod.CHECKPOINT_NAME)
        while time.time() < deadline and cp_level <= 0:
            try:
                cp_level = R.Checkpoint.load(cp_path).level
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile):
                pass
            time.sleep(0.02)
        if cp_level <= 0:
            return False, "no partial-verdict checkpoint before kill"
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()

    # restart (in-process incarnation on the same journal + WALs)
    d2 = serve_ns.CheckDaemon(
        serve_ns.ServeConfig(root=serve_dir, backend="tpu", workers=1))
    d2.start()
    details = [f"SIGKILL with checkpoint at level {cp_level}"]
    try:
        if d2.replay_stats.get("streams-resumed") != 1:
            return False, (f"replay resumed "
                           f"{d2.replay_stats.get('streams-resumed')}"
                           f" stream(s), want 1 "
                           f"(stats {d2.replay_stats})")
        st = d2.stream_status(sid)
        if st is None or st["ops"] != len(ops):
            return False, (f"WAL replay rebuilt "
                           f"{st and st['ops']}/{len(ops)} ops")
        details.append(f"WAL replay rebuilt all {len(ops)} ops")
        code, body, _ = d2.stream_close(sid, {"chunks": len(chunks)})
        if code != 200:
            return False, f"close after restart answered {code}: {body}"
        deadline = time.time() + 120
        st = {}
        while time.time() < deadline:
            st = d2.stream_status(sid) or {}
            if st.get("state") == "done" and "result" in st:
                break
            time.sleep(0.05)
        if st.get("state") != "done" or "result" not in st:
            return False, f"stream never finished after restart: {st}"
    finally:
        d2.drain(timeout_s=10)
        d2.stop()
    result = st["result"]
    resume_level = (result.get("stream") or {}).get("resume-level", 0)
    if resume_level <= 0:
        return False, (f"restart searched from level "
                       f"{resume_level} — checkpoint not resumed "
                       f"(stream {result.get('stream')})")
    details.append(f"resumed search at level {resume_level}, not 0")
    diff = [k for k in _STREAM_VERDICT_KEYS
            if result.get(k) != offline.get(k)]
    if diff:
        return False, (f"streamed verdict differs from offline on "
                       f"{diff}: {[result.get(k) for k in diff]} != "
                       f"{[offline.get(k) for k in diff]}")
    details.append(f"verdict {result['valid']} bit-identical to "
                   f"offline on {len(_STREAM_VERDICT_KEYS)} keys")
    return True, "; ".join(details)


def scenario_stream_dup(seed):
    """A duplicate / out-of-order chunk storm against the streaming
    intake: every chunk is sent twice, even-indexed chunks arrive
    before their predecessors, and an acked chunk is re-posted after
    close. The at-least-once contract says none of it may show — the
    sealed session's history.json must be BYTE-identical to a clean
    in-order session's, and the verdict identical to the offline
    analyze path (doc/serve.md "Streaming API")."""
    import tempfile

    from jepsen_tpu import serve as serve_ns
    from jepsen_tpu import stream as stream_mod
    from jepsen_tpu.checker import check_safe
    from jepsen_tpu.checker.wgl import linearizable
    from jepsen_tpu.history import History

    root = tempfile.mkdtemp(prefix="jepsen-chaos-streamdup-")
    h = simulate_register_history(240, n_procs=4, n_vals=4, seed=seed)
    ops = [o.to_dict() for o in h]
    offline = check_safe(linearizable(CASRegister(), backend="tpu"),
                         {"name": "chaos-streamdup-offline"},
                         History.of(ops))
    chunks = [ops[i:i + 20] for i in range(0, len(ops), 20)]

    daemon = serve_ns.CheckDaemon(
        serve_ns.ServeConfig(root=os.path.join(root, "serve"),
                             backend="tpu", workers=1))
    daemon.start()

    def run_session(tenant, storm):
        _, body, _ = daemon.stream_open({"tenant": tenant,
                                         "model": "cas-register"})
        sid = body["id"]
        dup = reordered = 0
        if storm:
            # pairwise swap + double-send: seq 1 lands before seq 0,
            # every chunk repeats, and chunk 0 is re-posted at the end
            order = []
            for i in range(0, len(chunks), 2):
                pair = ([i + 1, i] if i + 1 < len(chunks) else [i])
                order.extend(pair + pair)
            order.append(0)
        else:
            order = list(range(len(chunks)))
        for seq in order:
            code, body, _ = daemon.stream_append(
                sid, {"seq": seq, "ops": chunks[seq],
                      "crc": stream_mod.chunk_crc(chunks[seq])})
            if code != 202:
                return None, (f"{tenant} chunk {seq} answered "
                              f"{code}: {body}")
            dup += bool(body.get("duplicate"))
            reordered += bool(body.get("buffered"))
        code, body, _ = daemon.stream_close(sid, {"chunks": len(chunks)})
        if code != 200:
            return None, f"{tenant} close answered {code}: {body}"
        if storm:
            # at-least-once survives sealing: a late duplicate of an
            # acked chunk after close is absorbed, not an error
            code, body, _ = daemon.stream_append(
                sid, {"seq": 0, "ops": chunks[0],
                      "crc": stream_mod.chunk_crc(chunks[0])})
            if code != 202 or not body.get("duplicate"):
                return None, (f"{tenant} dup-after-close answered "
                              f"{code}: {body}")
        deadline = time.time() + 120
        st = {}
        while time.time() < deadline:
            st = daemon.stream_status(sid) or {}
            if st.get("state") == "done" and "result" in st:
                break
            time.sleep(0.05)
        if st.get("state") != "done" or "result" not in st:
            return None, f"{tenant} stream never finished: {st}"
        st["dup-sent"] = dup
        st["reordered-sent"] = reordered
        st["history-path"] = os.path.join(
            daemon.config.root, "streams", sid, stream_mod.HISTORY_NAME)
        return st, None

    try:
        clean, err = run_session("clean", storm=False)
        if err:
            return False, err
        storm, err = run_session("storm", storm=True)
        if err:
            return False, err
    finally:
        daemon.drain(timeout_s=10)
        daemon.stop()

    details = []
    if not storm["dup-sent"] or not storm["reordered-sent"]:
        return False, (f"storm was not a storm: {storm['dup-sent']} "
                       f"dup(s), {storm['reordered-sent']} reorder(s)")
    details.append(f"storm absorbed {storm['dup-sent']} duplicate and "
                   f"{storm['reordered-sent']} out-of-order chunk(s)")
    with open(clean["history-path"], "rb") as f:
        clean_bytes = f.read()
    with open(storm["history-path"], "rb") as f:
        storm_bytes = f.read()
    if clean_bytes != storm_bytes:
        return False, ("storm history.json differs from the clean "
                       "session's — intake is not idempotent")
    details.append(f"history.json byte-identical to the clean "
                   f"session's ({len(storm_bytes)} bytes)")
    for st in (clean, storm):
        diff = [k for k in _STREAM_VERDICT_KEYS
                if st["result"].get(k) != offline.get(k)]
        if diff:
            return False, (f"{st['tenant']} verdict differs from "
                           f"offline on {diff}")
    details.append(f"both verdicts ({offline['valid']}) identical to "
                   f"offline")
    return True, "; ".join(details)


def scenario_flightrec_kill(seed):
    """SIGKILL the daemon MID-BURST, after one poison request tripped
    its bucket's breaker: the breaker-trip flight-recorder dump written
    BEFORE the kill must survive whole (the atomic tmp + rename
    contract: valid JSON, never a half file), carry the poison
    request's trace id, and render through `jtpu flightrec` — while the
    SIGTERM-path dump is ABSENT, proving the dump came from the trip
    trigger, not from an orderly shutdown the kill never allowed
    (doc/observability.md, "Flight recorder")."""
    import contextlib
    import io
    import tempfile
    import urllib.request

    from jepsen_tpu import cli
    from jepsen_tpu.history import History
    from jepsen_tpu.obs import flightrec as flightrec_ns

    root = tempfile.mkdtemp(prefix="jepsen-chaos-flightrec-")
    serve_dir = os.path.join(root, "serve")
    port_file = os.path.join(root, "port.json")
    # same poison-by-row-count trick as serve-batch-poison: survivors
    # share a shape bucket with the poison, but only the poison's
    # packed row count triggers the injected gang OOM
    surv_ops = [[o.to_dict() for o in
                 simulate_register_history(40, n_procs=3, n_vals=3,
                                           seed=seed + i)]
                for i in range(3)]
    surv_ns = {pack_with_init(History.of(o), CASRegister())[0].n
               for o in surv_ops}
    poison_ops = poison_n = None
    for s in range(seed + 9, seed + 29):
        ops = [o.to_dict() for o in
               simulate_register_history(48, n_procs=3, n_vals=3,
                                         seed=s)]
        n = pack_with_init(History.of(ops), CASRegister())[0].n
        if n not in surv_ns:
            poison_ops, poison_n = ops, n
            break
    if poison_ops is None:
        return False, "poison history not distinguishable by row count"

    # breaker_fails=1: the poison's isolated failure trips the bucket
    # immediately, which fires the breaker-trip flight-recorder dump
    child_src = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from jepsen_tpu import serve as S\n"
        "from jepsen_tpu.checker import tpu as T\n"
        "def _fault(pks):\n"
        f"    if any(p.n == {poison_n} for p in pks):\n"
        "        raise RuntimeError("
        "'RESOURCE_EXHAUSTED: injected gang OOM (chaos)')\n"
        "T._GANG_FAULT = _fault\n"
        f"cfg = S.ServeConfig(root={serve_dir!r}, backend='tpu', "
        "workers=1, batch_max=8, batch_wait_ms=1000.0, "
        "breaker_fails=1)\n"
        f"d, srv = S.run_daemon(cfg, host='127.0.0.1', port=0, "
        f"store_root={root!r})\n"
        f"json.dump({{'port': srv.server_port}}, "
        f"open({port_file!r}, 'w'))\n"
        "d.drained.wait()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JTPU_TSDB="1",
               JTPU_TSDB_CADENCE="0.2")
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)

    def post(port, doc):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/check",
            data=json.dumps(doc).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.load(r)

    try:
        deadline = time.time() + 60
        port = None
        while time.time() < deadline:
            if os.path.exists(port_file):
                try:
                    with open(port_file) as f:
                        port = json.load(f)["port"]
                    break
                except (OSError, ValueError):
                    pass
            if proc.poll() is not None:
                return False, f"daemon exited rc={proc.returncode} at boot"
            time.sleep(0.1)
        if port is None:
            return False, "daemon never published its port"
        # poison leads the gang; survivors land inside the 1 s
        # coalesce window behind it
        poison_trace = post(port, {"tenant": "a",
                                   "model": "cas-register",
                                   "history": poison_ops}).get("trace")
        if not poison_trace:
            return False, "poison 202 carried no trace id"
        for i, o in enumerate(surv_ops):
            post(port, {"tenant": "ab"[i % 2],
                        "model": "cas-register", "history": o})
        # the kill window: the breaker has tripped (its dump is on
        # disk) but the burst is still being re-checked
        rec_dir = os.path.join(serve_dir, flightrec_ns.DIR_NAME)
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(f.startswith("breaker-trip-")
                   for f in (os.listdir(rec_dir)
                             if os.path.isdir(rec_dir) else [])):
                break
            if proc.poll() is not None:
                return False, (f"daemon died rc={proc.returncode} "
                               f"before the breaker tripped")
            time.sleep(0.05)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()

    details = []
    dumps = flightrec_ns.list_dumps(serve_dir)
    reasons = [d["reason"] for d in dumps]
    if "sigterm" in reasons or "drain" in reasons:
        return False, (f"orderly-shutdown dump present after SIGKILL "
                       f"({reasons}) — the kill was not a kill")
    trips = [d for d in dumps if d["reason"] == "breaker-trip"]
    if not trips:
        return False, (f"no breaker-trip dump survived the kill "
                       f"(found {reasons or 'none'})")
    leftovers = [f for f in os.listdir(os.path.join(
        serve_dir, flightrec_ns.DIR_NAME)) if f.startswith(".")]
    if leftovers:
        return False, f"half-written dump temp files survived: {leftovers}"
    doc = flightrec_ns.load_dump(serve_dir, trips[0]["name"])
    if doc is None:
        return False, f"breaker-trip dump {trips[0]['name']} unreadable"
    details.append(f"breaker-trip dump whole after SIGKILL "
                   f"({trips[0]['bytes']} bytes, "
                   f"{len(doc.get('spans') or [])} spans)")
    if (doc.get("extra") or {}).get("class") != "oom":
        return False, (f"dump blames class "
                       f"{(doc.get('extra') or {}).get('class')!r}, "
                       f"want 'oom'")
    if poison_trace not in (doc.get("trace-ids") or []):
        return False, (f"poison trace {poison_trace} missing from the "
                       f"dump's {len(doc.get('trace-ids') or [])} "
                       f"trace id(s)")
    details.append("dump carries the poison request's trace id")
    # the reader path: `jtpu flightrec` lists it, then renders it
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc_list = cli.run(cli.default_commands(),
                          ["flightrec", "--serve-dir", serve_dir])
    if rc_list != 0 or "breaker-trip" not in buf.getvalue():
        return False, (f"jtpu flightrec list rc={rc_list}, output "
                       f"{buf.getvalue()!r}")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc_show = cli.run(cli.default_commands(),
                          ["flightrec", trips[0]["name"],
                           "--serve-dir", serve_dir])
    if rc_show != 0 or f"trace {poison_trace}" not in buf.getvalue():
        return False, (f"jtpu flightrec {trips[0]['name']} rc="
                       f"{rc_show} did not render the poison trace")
    details.append("jtpu flightrec renders the dump (list + show)")
    return True, "; ".join(details)


def scenario_lint_seeded_race(seed):
    """Seed a known-bad concurrency pattern (off-lock queue append +
    depth bump — the exact bug class the lockset pass was built to
    catch) into a COPY of serve.py; assert LOCK-UNGUARDED fires on the
    seeded method and on nothing else new. The unpatched copy's
    findings are the control: only the delta counts, so pre-existing
    baselined findings can't mask (or fake) the signal."""
    import shutil
    import tempfile

    from jepsen_tpu.analysis import lockset_lint

    anchor = "    def _dequeue(self) -> Optional[CheckRequest]:"
    seeded_method = (
        "    def _seeded_bad_append(self, req):\n"
        "        q = self._queues.get(req.tenant)\n"
        "        if q is None:\n"
        "            q = self._queues[req.tenant] = deque()\n"
        "        q.append(req)\n"
        "        self._depth += 1\n"
        "        return q\n"
        "\n"
    )
    src_path = os.path.join(REPO, "jepsen_tpu", "serve.py")
    with open(src_path, encoding="utf-8") as f:
        src = f.read()
    if src.count(anchor) != 1:
        return False, (f"insertion anchor matched {src.count(anchor)} "
                       f"time(s) in serve.py (need exactly 1) — update "
                       f"the seeded-race anchor to track the refactor")

    with tempfile.TemporaryDirectory(prefix="jtpu-seeded-race-") as td:
        pkg = os.path.join(td, "jepsen_tpu")
        os.makedirs(pkg)
        clean = os.path.join(pkg, "serve.py")
        shutil.copyfile(src_path, clean)
        control = {f.key() for f in lockset_lint.lint_file(clean, td)}

        with open(clean, "w", encoding="utf-8") as f:
            f.write(src.replace(anchor, seeded_method + anchor))
        seeded = {f.key() for f in lockset_lint.lint_file(clean, td)}

    delta = sorted(seeded - control)
    want = [k for k in delta
            if k.startswith("LOCK-UNGUARDED ")
            and "_seeded_bad_append" in k]
    if not want:
        return False, (f"lockset pass missed the seeded off-lock "
                       f"append (delta: {delta or 'empty'})")
    noise = [k for k in delta if "_seeded_bad_append" not in k]
    if noise:
        return False, (f"seeding one bad method changed unrelated "
                       f"findings: {noise}")
    return True, (f"seeded off-lock append caught: {len(want)} "
                  f"LOCK-UNGUARDED finding(s) on _seeded_bad_append, "
                  f"zero collateral findings")


SCENARIOS = (
    ("oom", scenario_oom),
    ("wedge", scenario_wedge),
    ("kill-mid-segment", scenario_kill_mid_segment),
    ("transient", scenario_transient),
    ("hung-client", scenario_hung_client),
    ("kill9-recover", scenario_kill9_recover),
    ("malformed-history", scenario_malformed_history),
    ("trace-integrity", scenario_trace_integrity),
    ("watched-kill", scenario_watched_kill),
    ("explain-kill", scenario_explain_kill),
    ("prof-kill", scenario_prof_kill),
    ("plan-rejects", scenario_plan_rejects),
    ("fleet-host-kill", scenario_fleet_host_kill),
    ("straggler-host", scenario_straggler_host),
    ("serve-kill", scenario_serve_kill),
    ("trace-request-kill", scenario_trace_request_kill),
    ("serve-batch-poison", scenario_serve_batch_poison),
    ("serve-fleet-host-kill", scenario_serve_fleet_host_kill),
    ("stream-kill", scenario_stream_kill),
    ("stream-dup", scenario_stream_dup),
    ("flightrec-kill", scenario_flightrec_kill),
    ("lint-seeded-race", scenario_lint_seeded_race),
)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--only", action="append", metavar="NAME",
                    choices=[n for n, _ in SCENARIOS],
                    help="run only these scenarios (repeatable)")
    args = ap.parse_args()

    selected = [(n, fn) for n, fn in SCENARIOS
                if not args.only or n in args.only]
    rows = []
    failed = 0
    for name, fn in selected:
        accel._reset_for_tests()
        t0 = time.time()
        try:
            ok, detail = fn(args.seed)
        except Exception as e:  # noqa: BLE001 — a crash is a failure
            ok, detail = False, f"crashed: {type(e).__name__}: {e}"
        finally:
            resilience._inject_fault = None
        rows.append((name, ok, time.time() - t0, detail))
        failed += 0 if ok else 1

    width = max(len(n) for n, *_ in rows)
    print(f"{'scenario':<{width}}  result  secs  detail")
    for name, ok, secs, detail in rows:
        print(f"{name:<{width}}  {'PASS' if ok else 'FAIL':<6}"
              f"  {secs:4.1f}  {detail}")
    print(f"\n{len(rows) - failed}/{len(rows)} scenarios passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
