#!/usr/bin/env python
"""CI profiling gate: run ONE profiled CPU-mode localkv check and
assert the merged host+device trace validates as Chrome/Perfetto JSON,
inside a wall-clock bound.

The device-profiling path (`JTPU_PROF=1` / `--profile`,
doc/observability.md "Device profiling") crosses four layers — the
jax.profiler capture in the supervised search, the capture-file parser,
the host/device clock merge, and the Chrome export — and a regression
in any of them would only surface on a real profiled run. This gate IS
that run, in CI terms: a real localkv suite (real daemons, real
sockets) checked through the device path with profiling on, then the
merged export validated structurally:

* the export is valid JSON with a non-empty ``traceEvents`` list where
  every event carries ``name`` + ``ph`` and complete events carry
  numeric ``ts``/``dur`` (what Perfetto's importer requires);
* the host trace contains ``checker.segment`` spans and a
  ``prof.capture`` anchor (the capture actually scoped the search);
* when the platform's profiler produced a readable capture (it does on
  the CPU backend), at least one device-track record merged in, with a
  ``pid`` parent link — the "kernel span nested under a host span"
  contract. A platform refusing capture is reported, not failed (the
  opt-in is specified to degrade to a silent no-op).

Usage: python tools/prof_gate.py [--budget SECONDS]
Exit code 0 iff the merged trace validates within the budget
(default 30 s; run next to tools/lint_gate.py and tools/bench_gate.py
in CI).
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JTPU_PROF"] = "1"
# small segments: several checkpointed device calls, so the capture
# demonstrably spans segment boundaries
os.environ.setdefault("JTPU_SEGMENT_ITERS", "64")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", type=float, default=30.0,
                    help="wall-clock bound for the whole gate (s)")
    ap.add_argument("--time-limit", type=int, default=4,
                    help="localkv workload seconds")
    args = ap.parse_args()
    t0 = time.time()

    from jepsen_tpu import cli, core
    from jepsen_tpu.obs import profiler, trace as trace_ns
    from jepsen_tpu.suites.localkv import localkv_test

    run_dir = os.path.join(
        tempfile.mkdtemp(prefix="jepsen-prof-gate-"), "local-kv", "run")
    test = localkv_test({"time-limit": args.time_limit,
                         "nemesis-period": 2, "backend": "tpu"})
    test["store-dir"] = run_dir
    test = core.run(test)
    if test["results"].get("valid") is not True:
        print(f"# prof-gate: FAILED — profiled localkv run did not "
              f"validate ({test['results'].get('valid')!r})",
              file=sys.stderr)
        return 1

    host, stats = trace_ns.read_trace(
        os.path.join(run_dir, trace_ns.TRACE_NAME))
    names = {r.get("name") for r in host}
    problems = []
    if "checker.segment" not in names:
        problems.append("no checker.segment host span recorded")
    captured = profiler.CAPTURE_SPAN in names
    dev, pstats = profiler.read_profile(run_dir)
    merged_dev = profiler.merge_into_host(host, dev)
    if captured and pstats["files"] and not merged_dev:
        problems.append("capture produced trace files but zero device "
                        "records merged")
    if merged_dev and not any(r.get("pid") for r in merged_dev):
        problems.append("no merged device record is parented under a "
                        "host span")

    # the merged export must validate as Chrome/Perfetto JSON
    export = os.path.join(os.path.dirname(run_dir), "chrome.json")
    rc = cli.run(cli.default_commands(),
                 ["trace", "export", "--store", run_dir, "-o", export])
    if rc != 0:
        problems.append(f"trace export exited {rc}")
    else:
        try:
            with open(export) as f:
                doc = json.load(f)
            evs = doc.get("traceEvents")
            if not isinstance(evs, list) or not evs:
                problems.append("export has no traceEvents")
            else:
                for e in evs:
                    if "name" not in e or "ph" not in e:
                        problems.append(f"malformed event: {e!r:.80}")
                        break
                    if e["ph"] == "X" and not (
                            isinstance(e.get("ts"), (int, float))
                            and isinstance(e.get("dur"), (int, float))):
                        problems.append(
                            f"complete event without numeric ts/dur: "
                            f"{e!r:.80}")
                        break
        except ValueError as e:
            problems.append(f"export is not valid JSON: {e}")

    wall = time.time() - t0
    if wall > args.budget:
        problems.append(f"gate overran its {args.budget:.0f}s budget "
                        f"({wall:.1f}s)")

    print(f"# prof-gate: {stats['spans']} host span(s), "
          f"{pstats['files']} capture file(s), {len(merged_dev)} device "
          f"record(s) merged"
          + ("" if captured else
             " (platform refused capture: opt-in degraded to no-op)")
          + f", {wall:.1f}s")
    if problems:
        for p in problems:
            print(f"# prof-gate: FAILED — {p}", file=sys.stderr)
        return 1
    print("# prof-gate: merged trace validates as Chrome/Perfetto JSON")
    return 0


if __name__ == "__main__":
    sys.exit(main())
