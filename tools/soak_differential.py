"""Randomized differential soak: native vs Python WGL vs jitlin (vs the
device search every 7th round) across all kernel families, random
shapes/seeds, until the deadline. Any disagreement prints MISMATCH and
exits 1.

Usage:  python tools/soak_differential.py [seconds=1200]

This is the long-running counterpart of tests/test_native_wgl.py's
bounded differential tests — run it when touching any engine.
(A 30-minute soak: ~500k random histories, 0 mismatches.)"""
import random, sys, time
import os
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
# 8 virtual CPU devices BEFORE jax init: the keyed rounds fuzz the
# mesh-sharded batching/padding/escalation plumbing, not just 1-device.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")

from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.checker.native import check_history_native
from jepsen_tpu.checker.wgl import check_model
from jepsen_tpu.checker.jitlin import check_jit_model
from jepsen_tpu.checker.tpu import check_history_tpu
from jepsen_tpu.models import (CASRegister, FIFOQueue, Mutex, SetModel,
                               UnorderedQueue)
from test_checker_tpu import (random_fifo_history, random_queue_history,
                              random_register_history, random_set_history)

DEADLINE = time.time() + float(sys.argv[1]) if len(sys.argv) > 1 else time.time() + 1200
rng = random.Random(int(time.time()))
rounds = 0
mism = 0


def gen_history(fam, r2, n_ops, n_procs):
    if fam == "stag":
        # staggered (rare-overlap) registers: the forced-fast-forward
        # regime, with occasional read corruption to fuzz refutation
        from jepsen_tpu.testing import (corrupt_one_read,
                                        simulate_register_history)
        h = simulate_register_history(
            n_ops, n_procs=n_procs, n_vals=4,
            seed=r2.getrandbits(30), crash_p=r2.choice([0.0, 0.15]),
            overlap_p=r2.choice([0.02, 0.1]))
        if r2.random() < 0.5:
            h = corrupt_one_read(h, r2)
        return h, CASRegister()
    if fam == "wide":
        # high-concurrency bursts (the WIDE_LADDER regime, small enough
        # for the Python oracle): every op of a round overlaps every
        # other, with occasional corruption so refutations get fuzzed
        from jepsen_tpu.testing import wide_history
        return (wide_history(r2.randint(8, 18), r2.randint(1, 2),
                             write_frac=0.4, seed=r2.getrandbits(30),
                             corrupt=r2.random() < 0.3),
                CASRegister())
    if fam == "reg":
        return (random_register_history(r2, n_procs=n_procs, n_ops=n_ops,
                                        n_vals=3, crash_p=0.2),
                CASRegister())
    if fam == "set":
        return (random_set_history(r2, n_procs=min(n_procs, 4),
                                   n_ops=n_ops, n_vals=4), SetModel())
    if fam == "queue":
        return (random_queue_history(r2, n_procs=min(n_procs, 4),
                                     n_ops=n_ops, n_vals=4),
                UnorderedQueue())
    return (random_fifo_history(r2, n_procs=min(n_procs, 3),
                                n_ops=n_ops), FIFOQueue())


from jepsen_tpu import parallel
from jepsen_tpu.checker.tpu import check_keyed_tpu
MESH = parallel.make_mesh()
kround = 0


def keyed_round(seed, cap):
    """Fuzz the mesh-sharded keyed batch (random key count — uneven
    batches exercise the n_required=0 padding — plus the two-rung
    escalation) against the per-key Python oracle."""
    global mism
    r2 = random.Random(seed)
    fam = r2.choice(["reg", "set", "queue", "fifo", "stag"])
    pairs = [gen_history(fam, random.Random(seed + 31 * k),
                         r2.randint(6, 16), r2.randint(2, 5))
             for k in range(r2.randint(3, 12))]
    keyed = {k: h for k, (h, _) in enumerate(pairs)}
    model = pairs[0][1]
    out = check_keyed_tpu(keyed, model, mesh=MESH,
                          ladder=((16, 16, 8), (256, 32, 64)))
    for k, hk in keyed.items():
        want_k = check_model(hk, model, max_configs=cap)["valid"]
        got_k = out["results"][k]["valid"]
        if UNKNOWN in (want_k, got_k) or got_k is want_k:
            continue
        mism += 1
        print(f"KEYED MISMATCH fam={fam} seed={seed} key={k}: "
              f"device={got_k} python={want_k}", flush=True)
        if mism >= 5:
            sys.exit(1)


while time.time() < DEADLINE:
    rounds += 1
    seed = rng.getrandbits(32)
    r2 = random.Random(seed)
    fam = rng.choice(["reg", "set", "queue", "fifo", "stag"])
    if rounds % 11 == 0:
        # wide rounds are ~50x costlier (oracle + per-shape compiles):
        # sample them instead of letting them throttle the soak
        fam = "wide"
    n_ops = rng.randint(6, 16)
    n_procs = rng.randint(2, 5)
    h, model = gen_history(fam, r2, n_ops, n_procs)
    # Exact linearizability is NP-hard: one-in-hundreds-of-thousands
    # histories hit an exponential region (a 16-op queue history once ran
    # ~20 min / 11 GB in the Python engine before agreeing). A config
    # budget turns those rounds into skips instead of stalls.
    cap = 2_000_000
    want = check_model(h, model, max_configs=cap)["valid"]
    if want is UNKNOWN:
        continue
    got_n = check_history_native(h, model, max_configs=cap)["valid"]
    got_j = check_jit_model(h, model, cap)["valid"]
    verdicts = {"python": want, "native": got_n, "jit": got_j}
    if rounds % 7 == 0:  # device path is slow; sample it
        dres = check_history_tpu(h, model)
        if dres is not None:
            verdicts["device"] = dres["valid"]
    if rounds % 13 == 0:  # keyed mesh-sharded batch: padding/escalation
        kround += 1
        keyed_round(seed, cap)
    bad = {k: v for k, v in verdicts.items()
           if v is not UNKNOWN and v is not want}
    if bad:
        mism += 1
        print(f"MISMATCH fam={fam} seed={seed} n_ops={n_ops} "
              f"n_procs={n_procs}: {verdicts}", flush=True)
        if mism >= 5:
            sys.exit(1)
    if rounds % 500 == 0:
        print(f"# {rounds} rounds ({kround} keyed), {mism} mismatches",
              flush=True)
print(f"DONE {rounds} rounds ({kround} keyed), {mism} mismatches")
sys.exit(1 if mism else 0)
