#!/usr/bin/env python
"""CI bench gate: compare the newest ``BENCH_r*.json`` record against
the trajectory of prior runs and exit nonzero on a wall-clock
regression.

The driver appends one ``BENCH_rNN.json`` per round (the bench.py
contract line under ``parsed``), and until this gate the trajectory
just piled up — a 2x slowdown would ship unnoticed. The gate:

* parses every ``BENCH_r*.json`` in the repo root (``--root``), keeping
  records whose ``parsed.value`` is a number;
* compares the NEWEST record's ``value`` (warm seconds — the headline)
  and ``cold_s`` against the MEDIAN of prior same-platform records
  (a tpu number must not be judged against a cpu-fallback trajectory);
* flags a regression when ``newest > median * tolerance``. Warm is a
  steady-state measurement, so the band is tight (``--tolerance``,
  default 1.5x); cold includes XLA compilation whose cache hit/miss
  varies run to run, so its band is loose (``--cold-tolerance``,
  default 4.0x).

Fewer than two comparable prior records passes with a note — a gate
that fails on an empty trajectory would block the first rounds.

Usage: python tools/bench_gate.py [--root DIR] [--tolerance X]
       [--cold-tolerance X] [--format json]
Exit code 0 iff the newest record is within both bands (documented
next to tools/lint_gate.py — run both in CI).
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_records(root):
    """(n, path, parsed) for every BENCH_r*.json, ordered by round
    number; ``parsed`` is None for rounds that crashed or emitted no
    contract line."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            out.append((int(m.group(1)), path, None))
            continue
        parsed = doc.get("parsed")
        n = doc.get("n", int(m.group(1)))
        out.append((int(n), path, parsed
                    if isinstance(parsed, dict) else None))
    return sorted(out)


def _median(vals):
    vals = sorted(vals)
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2


#: Phase axes a regression is attributed to (dotted paths into the
#: record; bench.py emits the `compile` sub-record and `transfer_mb`
#: from the registry deltas around its cold+warm checks, and the
#: `search` sub-record's rebalance axes — remesh/steal counts and the
#: peak shard-imbalance ratio — so an elastic-fleet regression is
#: attributed like the compile/execute phases are, plus the counter-
#: lane analytics axes — dup-rate and frontier-area — so a pruning
#: regression names itself the same way).
ATTRIBUTION_AXES = ("compile_s", "execute_s", "transfer_mb",
                    "compile.cold_compile_s", "compile.warm_execute_s",
                    "search.remesh_count", "search.steal_count",
                    "search.imbalance_ratio",
                    "search.dup_rate", "search.frontier_area")


def _get_path(rec, path):
    cur = rec
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _attribution(newest, priors):
    """When the gate fires, say WHICH phase moved: newest vs the median
    of priors for each attribution axis, ratio-sorted so the likely
    driver (cold-compile, execute, or transfer) leads. Axes without a
    numeric newest value or two comparable priors are skipped."""
    rows = []
    for name in ATTRIBUTION_AXES:
        new_v = _get_path(newest, name)
        prior_vals = [v for v in (_get_path(p, name) for p in priors)
                      if isinstance(v, (int, float))]
        if not isinstance(new_v, (int, float)) or len(prior_vals) < 2:
            continue
        med = _median(prior_vals)
        if med > 0:
            ratio = new_v / med
        else:
            ratio = float("inf") if new_v > 0 else 1.0
        rows.append({"axis": name, "newest": round(new_v, 3),
                     "median": round(med, 3), "ratio": round(ratio, 2)})
    rows.sort(key=lambda r: -r["ratio"])
    return rows


def _check_axis(name, newest, priors, tolerance):
    """One comparison axis (value / cold_s). Returns a verdict dict;
    ``status`` is 'ok' | 'regression' | 'skipped'."""
    new_v = newest.get(name)
    prior_vals = [p[name] for p in priors
                  if isinstance(p.get(name), (int, float))]
    if not isinstance(new_v, (int, float)):
        return {"axis": name, "status": "skipped",
                "note": "newest record has no numeric value"}
    if len(prior_vals) < 2:
        return {"axis": name, "status": "skipped", "newest": new_v,
                "note": f"only {len(prior_vals)} comparable prior "
                        f"record(s); need 2"}
    med = _median(prior_vals)
    limit = med * tolerance
    status = "regression" if new_v > limit else "ok"
    return {"axis": name, "status": status, "newest": new_v,
            "median": round(med, 3), "tolerance": tolerance,
            "limit": round(limit, 3), "priors": len(prior_vals)}


def gate(root, tolerance=1.5, cold_tolerance=4.0):
    """The whole gate as data: {records, platform, checks, ok}."""
    records = load_records(root)
    parsed = [(n, p) for n, _, p in records if p is not None]
    doc = {"records": len(records), "parsed": len(parsed),
           "checks": [], "ok": True}
    if not parsed:
        doc["note"] = "no parseable BENCH records; nothing to gate"
        return doc
    newest_n, newest = parsed[-1]
    doc["newest"] = newest_n
    if newest.get("value") is None:
        # the newest round crashed or fell through every backend: that
        # is a failure in its own right, not a silent pass
        doc["ok"] = False
        doc["note"] = (f"newest record r{newest_n:02d} carries no "
                       f"measurement (error: "
                       f"{newest.get('error', 'unknown')!r})")
        return doc
    platform = newest.get("platform")
    doc["platform"] = platform
    # same-platform priors only: a tpu 8.9 s and a cpu 0.6 s measure
    # different machines, and a median across them gates nothing
    priors = [p for n, p in parsed[:-1]
              if n != newest_n and p.get("platform") == platform]
    doc["comparable-priors"] = len(priors)
    for axis, tol in (("value", tolerance),
                      ("cold_s", cold_tolerance)):
        check = _check_axis(axis, newest, priors, tol)
        doc["checks"].append(check)
        if check["status"] == "regression":
            doc["ok"] = False
    if not doc["ok"]:
        # regression attribution: which phase moved — cold-compile,
        # execute, or transfer — so the failure message names a
        # suspect instead of just a wall-clock number
        doc["attribution"] = _attribution(newest, priors)
    return doc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=REPO,
                    help="directory holding the BENCH_r*.json "
                         "trajectory (default: the repo root)")
    ap.add_argument("--tolerance", type=float, default=1.5,
                    help="warm-value band: newest > median * T fails")
    ap.add_argument("--cold-tolerance", type=float, default=4.0,
                    help="cold_s band (loose: compile-cache variance)")
    ap.add_argument("--format", default="text",
                    choices=["text", "json"])
    args = ap.parse_args()

    doc = gate(args.root, tolerance=args.tolerance,
               cold_tolerance=args.cold_tolerance)
    if args.format == "json":
        print(json.dumps(doc, indent=2))
    else:
        print(f"# bench-gate: {doc['parsed']}/{doc['records']} "
              f"record(s) parsed"
              + (f", newest r{doc['newest']:02d} "
                 f"({doc.get('platform')}, "
                 f"{doc.get('comparable-priors')} comparable "
                 f"prior(s))" if "newest" in doc else ""))
        for c in doc["checks"]:
            if c["status"] == "skipped":
                print(f"# bench-gate: {c['axis']}: skipped "
                      f"({c['note']})")
            else:
                print(f"# bench-gate: {c['axis']}: {c['status']} — "
                      f"newest {c['newest']}s vs median {c['median']}s "
                      f"x{c['tolerance']} = {c['limit']}s limit")
        if doc.get("note"):
            print(f"# bench-gate: {doc['note']}")
        for a in doc.get("attribution") or []:
            moved = "moved" if a["ratio"] > 1.2 else "flat"
            print(f"# bench-gate: attribution: {a['axis']} {moved} "
                  f"{a['ratio']}x (median {a['median']} -> "
                  f"{a['newest']})")
        print("# bench-gate: " + ("clean" if doc["ok"]
                                  else "FAILED — bench regression"))
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
