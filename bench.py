#!/usr/bin/env python
"""North-star benchmark: linearizability-check a 10k-op etcd-style CAS
register history on the attached accelerator.

Baseline (BASELINE.md): the reference's checker (knossos on a 32 GB JVM)
needs output truncation because results can take hours; the driver target is
"10k-op history checked in < 60 s on TPU". vs_baseline = 60 / seconds, so
1.0 == on-target, higher is better.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

N_OPS = 10_000
N_PROCS = 5
TARGET_S = 60.0
CAPACITY = 1024


def main():
    from jepsen_tpu.checker.tpu import check_history_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    import jax

    dev = jax.devices()[0]
    print(f"# device: {dev.platform} {getattr(dev, 'device_kind', '')}",
          file=sys.stderr)

    print(f"# synthesizing {N_OPS}-op register history...", file=sys.stderr)
    t0 = time.time()
    history = simulate_register_history(
        N_OPS, n_procs=N_PROCS, n_vals=16, seed=42, crash_p=0.002)
    print(f"# synthesized {len(history)} events in {time.time()-t0:.1f}s",
          file=sys.stderr)

    # Warm-up: same op count => same padded bucket => shared compilation.
    t0 = time.time()
    warm = simulate_register_history(N_OPS, n_procs=N_PROCS, n_vals=16,
                                     seed=7, crash_p=0.002)
    r = check_history_tpu(warm, CASRegister(), capacity=CAPACITY)
    print(f"# warm-up (incl. compile): {time.time()-t0:.1f}s -> {r['valid']}",
          file=sys.stderr)

    t0 = time.time()
    result = check_history_tpu(history, CASRegister(), capacity=CAPACITY)
    dt = time.time() - t0
    print(f"# check: valid={result['valid']} levels={result.get('levels')} "
          f"in {dt:.2f}s", file=sys.stderr)
    if result["valid"] is not True:
        # A wrong or unknown verdict on a valid-by-construction history is a
        # bench failure, not a number.
        print(json.dumps({"metric": "cas-register-10k-op-linearize",
                          "value": None, "unit": "s", "vs_baseline": 0,
                          "error": f"verdict {result['valid']!r}"}))
        return 1

    print(json.dumps({
        "metric": "cas-register-10k-op-linearize",
        "value": round(dt, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_S / dt, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
