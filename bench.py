#!/usr/bin/env python
"""North-star benchmark: linearizability-check a 10k-op etcd-style CAS
register history on the attached accelerator.

Baseline (BASELINE.md): the reference's checker (knossos on a 32 GB JVM)
needs output truncation because results can take hours; the driver target is
"10k-op history checked in < 60 s on TPU". vs_baseline = 60 / seconds, so
1.0 == on-target, higher is better.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

N_OPS = 10_000
N_PROCS = 5
TARGET_S = 60.0
CAPACITY = None  # auto-escalation ladder


def main():
    import jax

    # Persistent compilation cache: driver re-runs skip the compile cost.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jepsen_tpu_jit_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # noqa: BLE001 (older jax)
        pass

    from jepsen_tpu.checker.tpu import (
        check_history_tpu, pack_with_init, warm_ladder)
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    dev = jax.devices()[0]
    print(f"# device: {dev.platform} {getattr(dev, 'device_kind', '')}",
          file=sys.stderr)

    print(f"# synthesizing {N_OPS}-op register history...", file=sys.stderr)
    t0 = time.time()
    history = simulate_register_history(
        N_OPS, n_procs=N_PROCS, n_vals=16, seed=42, crash_p=0.002)
    print(f"# synthesized {len(history)} events in {time.time()-t0:.1f}s",
          file=sys.stderr)

    # Warm-up: same op count => same padded bucket => shared compilation.
    # Compile every escalation rung the timed check could touch.
    t0 = time.time()
    warm = simulate_register_history(N_OPS, n_procs=N_PROCS, n_vals=16,
                                     seed=7, crash_p=0.002)
    packed, kernel = pack_with_init(warm, CASRegister())
    warm_ladder(packed, kernel, rungs=3)
    r = check_history_tpu(warm, CASRegister())
    print(f"# warm-up (incl. compiles): {time.time()-t0:.1f}s -> "
          f"{r['valid']}", file=sys.stderr)

    t0 = time.time()
    result = check_history_tpu(history, CASRegister(), capacity=CAPACITY)
    dt = time.time() - t0
    print(f"# check: valid={result['valid']} levels={result.get('levels')} "
          f"in {dt:.2f}s", file=sys.stderr)
    _secondary_metrics()
    if result["valid"] is not True:
        # A wrong or unknown verdict on a valid-by-construction history is a
        # bench failure, not a number.
        print(json.dumps({"metric": "cas-register-10k-op-linearize",
                          "value": None, "unit": "s", "vs_baseline": 0,
                          "error": f"verdict {result['valid']!r}"}))
        return 1

    print(json.dumps({
        "metric": "cas-register-10k-op-linearize",
        "value": round(dt, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_S / dt, 2),
    }))
    return 0


def _secondary_metrics():
    """BASELINE.md's secondary configs, reported on stderr (the driver
    contract is one JSON line for the headline metric)."""
    import time as _t

    from jepsen_tpu.checker.tpu import check_keyed_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    # config 5: multi-key batched checking (the independent axis)
    keyed = {k: simulate_register_history(200, n_procs=5, n_vals=8,
                                          seed=1000 + k, crash_p=0.002)
             for k in range(50)}
    t0 = _t.time()
    out = check_keyed_tpu(keyed, CASRegister())
    dt = _t.time() - t0
    ok = sum(1 for r in out["results"].values() if r["valid"] is True)
    print(f"# secondary: 50 keys x 200 ops batched: {ok}/50 valid "
          f"in {dt:.2f}s (incl. compile)", file=sys.stderr)

    # config 2: single 2k-op history
    h = simulate_register_history(2000, n_procs=5, n_vals=8, seed=3,
                                  crash_p=0.002)
    from jepsen_tpu.checker.tpu import check_history_tpu
    t0 = _t.time()
    r = check_history_tpu(h, CASRegister())
    print(f"# secondary: 2k-op history: {r['valid']} in "
          f"{_t.time()-t0:.2f}s (incl. compile)", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
