#!/usr/bin/env python
"""North-star benchmark: linearizability-check a 10k-op etcd-style CAS
register history on the attached accelerator.

Baseline (BASELINE.md): the reference's checker (knossos on a 32 GB JVM)
needs output truncation because results can take hours; the driver target is
"10k-op history checked in < 60 s on TPU". vs_baseline = 60 / seconds, so
1.0 == on-target, higher is better.

Contract: prints EXACTLY one JSON line on stdout
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}
no matter what — TPU init failure, hang, or SIGTERM. Structure:

* orchestrator (this process, never imports jax): runs the measurement in a
  child subprocess so a hung/sick TPU plugin can be timed out and killed,
  retries TPU init once with backoff, then falls back to the CPU backend
  (pinning jax_platforms=cpu — the env var alone can be overridden by an
  ambient TPU plugin). A failure still emits a parseable record with an
  "error" field.
* child (JEPSEN_BENCH_CHILD=tpu|cpu): does the actual synth/warm-up/timed
  check and prints the JSON line, which the orchestrator relays.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_OPS = int(os.environ.get("JEPSEN_BENCH_N_OPS", "10000"))
N_PROCS = 5
TARGET_S = 60.0
METRIC = "cas-register-10k-op-linearize"
# Overall wall budget for the orchestrator; env-tunable for slower drivers.
BUDGET_S = float(os.environ.get("JEPSEN_BENCH_BUDGET_S", "1200"))

_emitted = False


def emit(value, vs_baseline, **extra):
    """Print the single contract line (at most once)."""
    global _emitted
    if _emitted:
        return
    _emitted = True
    rec = {"metric": METRIC, "value": value, "unit": "s",
           "vs_baseline": vs_baseline}
    rec.update(extra)
    print(json.dumps(rec))
    sys.stdout.flush()


# ---------------------------------------------------------------------------
# Child: the actual measurement (runs with a known-good backend)
# ---------------------------------------------------------------------------


def child_main(platform: str) -> int:
    import jax

    if platform == "cpu":
        # The env var alone is insufficient: an ambient TPU plugin (axon)
        # can re-register itself; the config update is authoritative.
        jax.config.update("jax_platforms", "cpu")

    # Persistent compilation cache: driver re-runs skip the compile cost.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jepsen_tpu_jit_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # noqa: BLE001 (older jax)
        pass

    from jepsen_tpu.checker.tpu import check_history_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    dev = jax.devices()[0]
    print(f"# device: {dev.platform} {getattr(dev, 'device_kind', '')}",
          file=sys.stderr)

    print(f"# synthesizing {N_OPS}-op register history...", file=sys.stderr)
    t0 = time.time()
    history = simulate_register_history(
        N_OPS, n_procs=N_PROCS, n_vals=16, seed=42, crash_p=0.002)
    print(f"# synthesized {len(history)} events in {time.time()-t0:.1f}s",
          file=sys.stderr)

    # Ahead-of-time search-plan forecast (doc/plan.md): the candidate
    # rung universe, the cheapest valid rung and its predicted
    # footprint vs the device byte budget — printed before any device
    # time so a config this bench would burn minutes discovering is
    # rejected/derated here is visible up front.
    from jepsen_tpu.checker.plan import summary_line as _plan_summary
    print(_plan_summary(history, CASRegister()), file=sys.stderr)

    # COLD: time-to-first-verdict, compiles included. Host-side rung
    # selection means exactly one rung compiles for this (low-
    # concurrency) shape; with a populated persistent cache even that
    # compile is skipped — the orchestrator runs a second cold child to
    # record the cached-cold number.
    from jepsen_tpu.checker.tpu import (compile_delta, compile_line,
                                        compile_snapshot,
                                        persistent_cache_dir)
    comp0 = compile_snapshot()
    t0 = time.time()
    result = check_history_tpu(history, CASRegister())
    cold = time.time() - t0
    cold_comp = compile_delta(comp0)
    print(f"# cold check (incl. compile): valid={result['valid']} "
          f"levels={result.get('levels')} in {cold:.2f}s", file=sys.stderr)

    # WARM: steady-state search time, compilation cached in-process.
    comp1 = compile_snapshot()
    t0 = time.time()
    result2 = check_history_tpu(history, CASRegister())
    warm = time.time() - t0
    warm_comp = compile_delta(comp1)
    print(f"# warm check: valid={result2['valid']} in {warm:.2f}s",
          file=sys.stderr)
    # cold/warm wall-clock attribution (doc/observability.md "Compile
    # accounting"): which share of each check was XLA compilation vs
    # execution vs host work — the split the warm-executable-cache
    # daemon (ROADMAP item 1) must drive to zero cold shapes.
    print(compile_line(cold_comp, cold), file=sys.stderr)
    print(compile_line(warm_comp, warm), file=sys.stderr)

    if result["valid"] is not True or result2["valid"] is not True:
        # A wrong or unknown verdict on a valid-by-construction history is
        # a bench failure, not a number.
        print(json.dumps({"metric": METRIC, "value": None, "unit": "s",
                          "vs_baseline": 0, "platform": dev.platform,
                          "error": f"verdict {result['valid']!r}"}))
        return 1

    # Contract line FIRST: if a slow device makes the secondaries blow
    # the orchestrator's timeout, the headline is already on stdout (and
    # the orchestrator salvages a timed-out child's output).
    rec = {
        "metric": METRIC,
        "value": round(warm, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_S / warm, 2),
        "platform": dev.platform,
        "cold_s": round(cold, 3),
        "cold_vs_baseline": round(TARGET_S / cold, 2),
    }
    # compile/execute split from the obs layer (doc/observability.md):
    # the supervised search reports host-measured device phases, so
    # BENCH_*.json can attribute the cold number to XLA compilation vs
    # actual search execution. Cold ran first, so its device-s carries
    # the compile phase; warm's is pure execute.
    split = result.get("device-s") or {}
    split2 = result2.get("device-s") or {}
    if split or split2:
        rec["compile_s"] = round(split.get("compile", 0.0), 3)
        rec["execute_s"] = round(split2.get("execute", 0.0)
                                 or split.get("execute", 0.0), 3)
    # compile-cache attribution in the BENCH record (registry deltas
    # around the cold and warm checks): bench_gate.py reads these to
    # say WHICH phase moved when the trajectory regresses.
    rec["compile"] = {
        "cold_shapes": int(cold_comp["cold"]),
        "cold_compile_s": round(cold_comp["compile-s"], 3),
        "warm_cache_hits": int(warm_comp["cache-hits"]),
        "warm_execute_s": round(warm_comp["execute-s"], 3),
        "persistent_cache": persistent_cache_dir() is not None,
        "persistent_hits": int(cold_comp["persistent-hits"]
                               + warm_comp["persistent-hits"]),
    }
    rec["transfer_mb"] = round(
        (cold_comp["transfer-bytes"] + warm_comp["transfer-bytes"])
        / 1e6, 3)
    # rebalance accounting (doc/resilience.md "Elastic fleet"): remesh/
    # steal counts and the peak shard-imbalance ratio land in the bench
    # record so tools/bench_gate.py attributes a rebalance regression
    # the way it attributes the compile/execute phases. 0/0/1.0 on
    # non-fleet non-sharded runs — the axes must exist to be gated.
    rec["search"] = _search_axes([result, result2])
    print(json.dumps(rec))
    sys.stdout.flush()
    _search_line("10k headline", result2, warm)
    _util_line("headline", warm, [result2])

    if not os.environ.get("JEPSEN_BENCH_SKIP_SECONDARY"):
        # Soft deadline (orchestrator-set): a child SIGKILLed mid-TPU-use
        # can leave the chip lease stuck for minutes, hanging the NEXT
        # child's init — so the child checks the clock between secondaries
        # and exits cleanly (releasing the device) before the hard kill.
        deadline = float(os.environ.get("JEPSEN_BENCH_CHILD_DEADLINE")
                         or "0") or None

        # Each stage is (label, fn, headroom): headroom is the seconds of
        # soft-deadline slack a stage needs to START — a rough upper bound
        # on its own runtime, so it finishes before the orchestrator's
        # hard kill (a SIGKILL mid-TPU-use wedges the chip lease for the
        # next child). Short on slack, a stage is skipped (later, cheaper
        # stages still get their chance); past the deadline itself the
        # child exits cleanly to release the device. CPU keeps the
        # historical order (wide first: no init cost, no lease to wedge)
        # and zero headrooms (nothing to wedge on a SIGKILL).
        wide = lambda: _wide_history_comparison(deadline)  # noqa: E731
        if dev.platform != "cpu":
            stages = [
                ("staggered", _staggered_comparison, 30.0),
                ("recovery", lambda: _recovery_overhead(history), 60.0),
                ("keyed", lambda: _keyed_batch_comparison(dev.platform), 120.0),
                ("tuning sweep", lambda: _tpu_tuning_sweep(history), 90.0),
                ("secondary metrics",
                 lambda: _secondary_metrics(deadline), 300.0),
                ("wide", wide, 180.0),
            ]
        else:
            stages = [
                ("wide", wide, 0.0),
                ("staggered", _staggered_comparison, 0.0),
                ("recovery", lambda: _recovery_overhead(history), 0.0),
                ("keyed", lambda: _keyed_batch_comparison(dev.platform), 0.0),
                ("secondary metrics",
                 lambda: _secondary_metrics(deadline), 0.0),
            ]
        for label, fn, headroom in stages:
            if deadline is not None:
                now = time.time()
                if now > deadline:
                    print(f"# secondaries: soft deadline hit before {label};"
                          f" exiting cleanly to release the device",
                          file=sys.stderr)
                    return 0
                if now > deadline - headroom:
                    print(f"# secondaries: skipping {label} (needs "
                          f"~{headroom:.0f}s of soft-deadline slack)",
                          file=sys.stderr)
                    continue
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — must not eat the line
                print(f"# {label} failed: {e!r}", file=sys.stderr)
    return 0


def _search_axes(results):
    """Rebalance + search-analytics axes for the bench record: total
    remesh/steal counts and the peak shard-imbalance ratio across the
    measured checks (fleet results carry a ``fleet`` entry, sharded
    results a ``shard-balance`` entry; plain runs gate at 0/0/1.0),
    plus the counter-lane rollup where a result carries one
    (``searchstats``: dup-rate / frontier-area / prune-efficiency,
    doc/observability.md "Search analytics") — a pruning regression is
    attributed the way the compile/execute phases are. JTPU_TRACE=0
    runs carry no rollup and gate at 0.0/0/0.0."""
    remesh = steal = 0
    imb = 1.0
    dup = prune = 0.0
    area = 0
    for r in results:
        if not isinstance(r, dict):
            continue
        fl = r.get("fleet") or {}
        remesh += int(fl.get("remesh-count") or 0)
        steal += int(fl.get("steal-count") or 0)
        for cand in (fl.get("peak-imbalance"),
                     (r.get("shard-balance") or {}).get(
                         "imbalance-ratio")):
            if isinstance(cand, (int, float)):
                imb = max(imb, float(cand))
        ss = r.get("searchstats") or {}
        dup = max(dup, float(ss.get("dup-rate") or 0.0))
        prune = max(prune, float(ss.get("prune-efficiency") or 0.0))
        area += int(ss.get("frontier-area") or 0)
    return {"remesh_count": remesh, "steal_count": steal,
            "imbalance_ratio": round(imb, 3),
            "dup_rate": round(dup, 4), "frontier_area": area,
            "prune_efficiency": round(prune, 4)}


def _search_line(label, result, wall_s):
    """One '# search:' stderr line attributing a check's wall-clock to
    compile/device/host phases, from the telemetry the supervised
    search surfaces (device-s, segment-levels, frontier-hwm,
    transfer-bytes — doc/observability.md). Host time is the wall
    minus the device phases: packing, gating, checkpoint snapshots,
    supervisor bookkeeping. Diagnostics only — never raises."""
    try:
        dev = result.get("device-s") or {}
        comp = float(dev.get("compile", 0.0))
        exe = float(dev.get("execute", 0.0))
        host = max(0.0, wall_s - comp - exe)
        line = (f"# search {label}: compile={comp:.3f}s "
                f"execute={exe:.3f}s host={host:.3f}s of "
                f"{wall_s:.3f}s wall")
        if result.get("segments"):
            segl = result.get("segment-levels") or []
            line += (f", {result['segments']} segment(s)"
                     + (f" x {max(segl)} level(s) max" if segl else ""))
        if result.get("frontier-hwm") is not None:
            line += f", frontier-hwm={result['frontier-hwm']} rows"
        if result.get("transfer-bytes"):
            line += (f", {result['transfer-bytes'] / 1e6:.1f} MB "
                     f"transferred")
        ss = result.get("searchstats")
        if ss:
            line += (f", dup-rate={ss.get('dup-rate', 0.0):.0%}"
                     f", trunc-losses={ss.get('trunc-losses', 0)}")
        bal = result.get("shard-balance")
        if bal:
            line += (f", shard-imbalance={bal['imbalance-ratio']}x "
                     f"over {bal['devices']} device(s)")
        fl = result.get("fleet")
        if fl:
            line += (f", fleet {len(fl.get('live') or [])}/"
                     f"{len(fl.get('hosts') or [])} host(s) "
                     f"{fl.get('remesh-count', 0)} remesh(es) "
                     f"{fl.get('steal-count', 0)} steal(s) "
                     f"peak-imbalance={fl.get('peak-imbalance')}x")
        print(line, file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# search {label}: accounting failed: {e!r}",
              file=sys.stderr)


def _util_line(label, seconds, results):
    """One '# util:' stderr line from the XLA cost-model accounting the
    checkers attach to their results (doc/observability.md): model
    FLOP/s and bytes-accessed/s achieved over the measured wall, plus
    the device-busy fraction where the result carries the device-s
    split. Replaces the old hand-rolled roofline estimate (analytic
    per-level work + a measured synthetic-sort ceiling): the compiler's
    own cost model prices the executables that actually ran, escalation
    rungs and crash grids included, with no shape bookkeeping to drift
    out of sync. ``results`` is a list of checker result dicts (for
    keyed checks, the TOP-level dict — per-key results deliberately
    carry no cost, see check_keyed_tpu). Diagnostics only — never
    raises (it must not be able to destroy the measurements it
    annotates)."""
    try:
        _util_line_inner(label, seconds, results)
    except Exception as e:  # noqa: BLE001
        print(f"# util {label}: accounting failed: {e!r}",
              file=sys.stderr)


def _util_line_inner(label, seconds, results):
    # each cost entry is one executable shape: flops / bytes-accessed
    # are per while-iteration (the HLO analysis counts a loop body
    # once), "levels" the iterations it ran, "unroll" the search steps
    # folded into each iteration
    tot_flops = tot_bytes = 0.0
    entries = 0
    busy = 0.0
    for r in results:
        for e in (r.get("cost") or []):
            iters = e.get("levels", 0) / max(e.get("unroll", 1), 1)
            tot_flops += e.get("flops", 0.0) * iters
            tot_bytes += e.get("bytes-accessed", 0.0) * iters
            entries += 1
        dev = r.get("device-s") or {}
        busy += float(dev.get("compile", 0.0)) \
            + float(dev.get("execute", 0.0))
    if not entries or seconds <= 0:
        return  # cost accounting off (JTPU_TRACE=0) or unavailable
    line = (f"# util {label}: {tot_flops / seconds / 1e9:.2f} GFLOP/s, "
            f"{tot_bytes / seconds / 1e6:,.0f} MB/s accessed "
            f"(XLA cost model, {entries} executable(s))")
    if busy:
        line += f", device busy {100 * busy / seconds:.0f}% of wall"
    print(line, file=sys.stderr)


def _wide_history_comparison(child_deadline=None):
    """The WIDTH regime — the device path's structural win. A register
    history with 100 fully-overlapping processes per round (the
    aerospike 100-thread CAS shape, reference aerospike/core.clj:566-575)
    makes the host DFS explode combinatorially: the C++ engine needs
    83M configs (measured 343 s on the round-4 build host; each run
    extrapolates its own host's rate below), while the pool search's
    expansion-heavy wide rungs decide the same history in ~6 s on the
    CPU *backend* alone (59x) — device wall-clock beats native wall-clock
    before an accelerator is even attached. Native is capped here to
    keep the bench bounded; the cap counts as a loss at the cap."""
    import time as _t

    from jepsen_tpu.checker.native import available, check_history_native
    from jepsen_tpu.checker.tpu import check_history_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import wide_history

    h = wide_history(100, 4, write_frac=0.2, seed=3)
    t0 = _t.time()
    r = check_history_tpu(h, CASRegister())
    cold = _t.time() - t0
    t0 = _t.time()
    r = check_history_tpu(h, CASRegister())
    warm = _t.time() - t0
    line = (f"# wide-100x4 (400 ops, window ~100): device {r['valid']} "
            f"warm={warm:.2f}s cold={cold:.2f}s")
    _util_line("wide-100x4", warm, [r])
    if available():
        cap_s = 120.0
        # Clamp the native side's budget to the child's soft deadline
        # (when set): wide is the stage most likely to be in flight when
        # the orchestrator's hard kill lands, and a SIGKILL mid-TPU-use
        # wedges the chip lease for the next child.
        if child_deadline is not None:
            cap_s = max(5.0, min(cap_s, child_deadline - _t.time()))
        deadline = _t.time() + cap_s
        t0 = _t.time()
        rn = check_history_native(
            h, CASRegister(), should_stop=lambda: _t.time() > deadline)
        tn = _t.time() - t0
        if rn["valid"] in (True, False):
            verdict = (f"native {rn['valid']} {tn:.2f}s "
                       f"cfgs={rn.get('configs-explored')}")
        else:
            # The DFS is deterministic, so the TOTAL config count to
            # decide this history (83M, measured once unbounded) is
            # machine-independent; extrapolate THIS host's rate over it
            # instead of quoting another machine's wall time.
            cfgs = rn.get("configs-explored") or 0
            est = tn * 83_000_000 / cfgs if cfgs else float("inf")
            verdict = (f"native gave up at {cap_s:.0f}s cap "
                       f"(cfgs={cfgs}; ~{est:.0f}s extrapolated to the "
                       f"83M-config full search at this host's rate)")
        line += " | " + verdict + \
            f" | device/native={warm / max(tn, 1e-9):.2f}x"
    print(line, file=sys.stderr)


def _tpu_tuning_sweep(history):
    """Measure the two device knobs on real hardware (VERDICT r03 #1b):
    JTPU_UNROLL (search steps per while_loop iteration) and the first
    escalation rung (slim best-first vs wide). Results go to stderr; the
    winning unroll can then be pinned via the env var for future runs."""
    import time as _t

    from jepsen_tpu.checker.tpu import check_history_tpu
    from jepsen_tpu.models import CASRegister

    prior = os.environ.get("JTPU_UNROLL")
    try:
        for u in (1, 2, 4):
            os.environ["JTPU_UNROLL"] = str(u)
            t0 = _t.time()
            r = check_history_tpu(history, CASRegister())
            cold = _t.time() - t0
            t0 = _t.time()
            check_history_tpu(history, CASRegister())
            warm = _t.time() - t0
            print(f"# sweep: unroll={u} warm={warm:.2f}s "
                  f"cold={cold:.2f}s (compile incl.) "
                  f"valid={r['valid']} levels={r.get('levels')}",
                  file=sys.stderr)
    finally:
        if prior is None:
            os.environ.pop("JTPU_UNROLL", None)
        else:
            os.environ["JTPU_UNROLL"] = prior
    for cap, exp, label in ((32, 4, "slim"), (128, 8, "default"),
                            (1024, 64, "wide")):
        t0 = _t.time()
        r = check_history_tpu(history, CASRegister(), capacity=cap,
                              expand=exp)
        cold = _t.time() - t0
        t0 = _t.time()
        check_history_tpu(history, CASRegister(), capacity=cap,
                          expand=exp)
        warm = _t.time() - t0
        print(f"# sweep: first-rung={label} ({cap}/{exp}) "
              f"warm={warm:.2f}s cold={cold:.2f}s valid={r['valid']} "
              f"levels={r.get('levels')}", file=sys.stderr)


def _recovery_overhead(history):
    """The resilient execution layer's price tag, on the headline
    history: (a) the monolithic single-while_loop search vs the default
    checkpointed segmented search — the steady-state overhead every run
    now pays for crash-survivability; (b) a search killed after two
    segments and resumed from its checkpoint — what a mid-run
    preemption actually costs vs re-running from scratch."""
    import time as _t

    from jepsen_tpu import resilience
    from jepsen_tpu.checker.tpu import check_history_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.ops.encode import pack_with_init

    def best_of(fn, n=2):
        best = float("inf")
        for _ in range(n):
            t0 = _t.time()
            fn()
            best = min(best, _t.time() - t0)
        return best

    prior = os.environ.get("JTPU_SEGMENT_ITERS")
    try:
        os.environ["JTPU_SEGMENT_ITERS"] = "0"
        check_history_tpu(history, CASRegister())   # absorb compile
        mono = best_of(lambda: check_history_tpu(history, CASRegister()))
        os.environ["JTPU_SEGMENT_ITERS"] = "1024"
        check_history_tpu(history, CASRegister())
        segd = best_of(lambda: check_history_tpu(history, CASRegister()))
    finally:
        if prior is None:
            os.environ.pop("JTPU_SEGMENT_ITERS", None)
        else:
            os.environ["JTPU_SEGMENT_ITERS"] = prior

    # kill-after-2-segments + checkpoint resume (small segments so the
    # search is guaranteed to span several): wall time of dying and
    # recovering, end to end
    p, kernel = pack_with_init(history, CASRegister())
    cps = []

    def killer(ctx):
        if ctx["segment"] == 2 and not cps[2:]:
            raise RuntimeError("bench-injected mid-run kill")

    t0 = _t.time()
    resilience._inject_fault = killer
    try:
        try:
            resilience.supervised_check_packed(
                p, kernel, segment_iters=128, on_checkpoint=cps.append)
        except RuntimeError:
            pass
    finally:
        resilience._inject_fault = None
    r = resilience.supervised_check_packed(
        p, kernel, segment_iters=128,
        resume=cps[-1] if cps else None)
    recov = _t.time() - t0
    print(f"# recovery: single-shot={mono:.3f}s "
          f"checkpointed={segd:.3f}s "
          f"(+{(segd / mono - 1) * 100:.0f}% steady-state), "
          f"kill@seg2+resume={recov:.3f}s valid={r['valid']} "
          f"segments={r.get('segments')} "
          f"({len(cps)} checkpoints taken)", file=sys.stderr)


def _staggered_comparison():
    """The REALISTIC workload shape: a 10k-op register history with rare
    overlap (the reference's tutorial workloads stagger ops, etcd.clj:172
    — most positions are forced runs). The device search's forced
    fast-forward collapses these from ~n levels to ~#concurrent regions:
    measured 546 levels / 0.054 s warm on the CPU backend vs 0.030 s
    native — near-parity where the device previously lost 30x."""
    import time as _t

    from jepsen_tpu.checker.native import available, check_history_native
    from jepsen_tpu.checker.tpu import check_history_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    h = simulate_register_history(N_OPS, n_procs=N_PROCS, n_vals=16,
                                  seed=42, crash_p=0.0, overlap_p=0.05)
    t0 = _t.time()
    r = check_history_tpu(h, CASRegister())
    cold = _t.time() - t0
    # Best of two warm runs: at ~50-100 ms this measurement occasionally
    # catches a 6x in-process hiccup (observed 0.39 s once against a
    # 0.047-0.116 s typical range across bench runs); the min is the
    # steady-state claim.
    warm = float("inf")
    for _ in range(2):
        t0 = _t.time()
        r = check_history_tpu(h, CASRegister())
        warm = min(warm, _t.time() - t0)
    line = (f"# staggered {N_OPS}-op (etcd-tutorial shape): device "
            f"{r['valid']} warm={warm:.3f}s cold={cold:.2f}s "
            f"levels={r.get('levels')}")
    _util_line("staggered", warm, [r])
    if available():
        t0 = _t.time()
        rn = check_history_native(h, CASRegister())
        tn = _t.time() - t0
        line += (f" | native {rn['valid']} {tn:.3f}s | "
                 f"device/native={warm / max(tn, 1e-9):.2f}x")
        if rn["valid"] is not r["valid"]:
            line += " ENGINE DISAGREEMENT"
    print(line, file=sys.stderr)


def _keyed_batch_comparison(platform: str):
    """The independent-key axis at scale, device vs native on the SAME
    workload (VERDICT r03 #1c): the device batch amortizes per-level
    overhead across every key, the regime where the accelerator should
    structurally beat the single-host thread pool."""
    import time as _t

    from jepsen_tpu.checker.native import available, check_keyed_native
    from jepsen_tpu.checker.tpu import check_keyed_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    n_keys, n_ops = (256, 2000) if platform != "cpu" else (64, 500)
    # Staggered measured FIRST: it is the near-parity claim and small
    # enough (~0.2 s standalone) that running it after the dense batch
    # inflates it ~0.15 s of in-process residue (allocator/thread-pool
    # state) — dense at ~2 s is insensitive to the same residue. Note
    # the cold= attribution moves with the order: whichever shape runs
    # first absorbs the shared keyed-path compile in its cold number
    # (warm=, the recorded claim, is unaffected).
    shapes = (
        # the realistic independent-key shape: staggered per-key
        # histories (etcd.clj:167-173 staggers 1/30 s) ride the
        # forced fast-forward — the configuration where the device
        # batch approaches/overtakes the native thread pool
        ("staggered", dict(crash_p=0.0, overlap_p=0.05)),
        ("dense", dict(crash_p=0.001)))
    for label, kw in shapes:
        keyed = {k: simulate_register_history(n_ops, n_procs=5, n_vals=8,
                                              seed=7000 + k, **kw)
                 for k in range(n_keys)}
        t0 = _t.time()
        out = check_keyed_tpu(keyed, CASRegister())
        cold = _t.time() - t0
        t0 = _t.time()
        out = check_keyed_tpu(keyed, CASRegister())
        warm = _t.time() - t0
        ok = sum(1 for r in out["results"].values()
                 if r["valid"] is True)
        line = (f"# keyed-batch {n_keys}x{n_ops} {label}: device "
                f"warm={warm:.2f}s cold={cold:.2f}s ({ok}/{n_keys} "
                f"valid)")
        _util_line(f"keyed-{label}", warm, [out])
        if available():
            t0 = _t.time()
            rn = check_keyed_native(keyed, CASRegister())
            native_s = _t.time() - t0
            nk = sum(1 for r in rn["results"].values()
                     if r["valid"] is True)
            line += (f" | native={native_s:.2f}s ({nk}/{n_keys} valid) "
                     f"| device/native="
                     f"{warm / max(native_s, 1e-9):.1f}x")
        print(line, file=sys.stderr)


def _secondary_metrics(deadline=None):
    """BASELINE.md's secondary configs, reported on stderr (the driver
    contract is one JSON line for the headline metric). ``deadline``
    (the child's soft deadline) gates the long-running 1M-op device
    stretch check."""
    import time as _t

    from jepsen_tpu.checker.tpu import check_history_tpu, check_keyed_tpu
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.testing import simulate_register_history

    # config 5: multi-key batched checking (the independent axis)
    keyed = {k: simulate_register_history(200, n_procs=5, n_vals=8,
                                          seed=1000 + k, crash_p=0.002)
             for k in range(50)}
    t0 = _t.time()
    out = check_keyed_tpu(keyed, CASRegister())
    dt = _t.time() - t0
    ok = sum(1 for r in out["results"].values() if r["valid"] is True)
    t0 = _t.time()
    check_keyed_tpu(keyed, CASRegister())
    warm_k = _t.time() - t0
    print(f"# secondary: 50 keys x 200 ops batched: {ok}/50 valid "
          f"in {dt:.2f}s (incl. compile; warm {warm_k:.2f}s)",
          file=sys.stderr)

    # config 2: single 2k-op history
    h = simulate_register_history(2000, n_procs=5, n_vals=8, seed=3,
                                  crash_p=0.002)
    t0 = _t.time()
    r = check_history_tpu(h, CASRegister())
    print(f"# secondary: 2k-op history: {r['valid']} in "
          f"{_t.time()-t0:.2f}s (incl. compile)", file=sys.stderr)

    # config 6: non-register model families on the device path
    from jepsen_tpu.history import History, Op
    from jepsen_tpu.models import SetModel, UnorderedQueue

    rows = []
    for v in range(300):
        rows.append(Op(type="invoke", f="add", value=v, process=v % 5,
                       time=2 * v))
        rows.append(Op(type="ok", f="add", value=v, process=v % 5,
                       time=2 * v + 1))
    rows.append(Op(type="invoke", f="read", value=None, process=9,
                   time=10_000))
    rows.append(Op(type="ok", f="read", value=sorted(range(300)),
                   process=9, time=10_001))
    t0 = _t.time()
    rs = check_history_tpu(History.of(rows), SetModel())
    print(f"# secondary: 300-add set + exact read: {rs['valid']} "
          f"backend={rs.get('backend')} in {_t.time()-t0:.2f}s",
          file=sys.stderr)

    rows = []
    t = 0
    for v in range(150):
        for f, val in (("enqueue", v), ("dequeue", v)):
            rows.append(Op(type="invoke", f=f,
                           value=val if f == "enqueue" else None,
                           process=0 if f == "enqueue" else 1, time=t))
            rows.append(Op(type="ok", f=f, value=val,
                           process=0 if f == "enqueue" else 1,
                           time=t + 1))
            t += 2
    t0 = _t.time()
    rq = check_history_tpu(History.of(rows), UnorderedQueue())
    print(f"# secondary: 300-op unique-value queue: {rq['valid']} "
          f"backend={rq.get('backend')} in {_t.time()-t0:.2f}s",
          file=sys.stderr)

    # config 7 (stretch): 10x the north star — a 100k-op history
    h = simulate_register_history(100_000, n_procs=N_PROCS, n_vals=16,
                                  seed=4, crash_p=0.0002)
    t0 = _t.time()
    r = check_history_tpu(h, CASRegister())
    print(f"# secondary: 100k-op history: {r['valid']} "
          f"levels={r.get('levels')} in {_t.time()-t0:.2f}s "
          f"(incl. compile)", file=sys.stderr)

    # config 7b (stretch): 100x — a 1M-op staggered history through the
    # DEVICE search (the native engine's 1M line is below; forced
    # fast-forward collapses ~1M levels to ~60k). Device warm measured
    # 16.5 s on the quiet CPU backend. Gated on the soft deadline: synth
    # + compile + search is the longest sub-check in this stage, and on
    # TPU an overrun means a SIGKILL mid-device-use (the lease wedge).
    if deadline is None or _t.time() < deadline - 120:
        h1m_dev = simulate_register_history(
            1_000_000, n_procs=N_PROCS, n_vals=16, seed=4,
            crash_p=0.0, overlap_p=0.05)
        t0 = _t.time()
        r = check_history_tpu(h1m_dev, CASRegister())
        print(f"# secondary: 1M-op staggered history (device): "
              f"{r['valid']} levels={r.get('levels')} in "
              f"{_t.time()-t0:.2f}s (incl. compile)", file=sys.stderr)
    else:
        print("# secondary: 1M-op device check skipped (soft deadline)",
              file=sys.stderr)

    # configs 1/3/4: the CPU-tier baselines — 200-op linearizable via
    # the host facade, and the counter/set/total-queue folds at 10k ops
    from jepsen_tpu.checker import linearizable
    from jepsen_tpu.checker.basic import counter, set_checker, total_queue
    from jepsen_tpu.models import CASRegister as _Reg

    h200 = simulate_register_history(200, n_procs=5, n_vals=8, seed=11)
    t0 = _t.time()
    r1 = linearizable(_Reg()).check({}, h200)
    print(f"# secondary: 200-op linearizable (host facade): {r1['valid']} "
          f"[{r1.get('engine', 'py')}] in {_t.time()-t0:.3f}s",
          file=sys.stderr)

    rows = []
    t = 0
    for v in range(5000):
        rows.append(Op(type="invoke", f="add", value=1, process=v % 5,
                       time=t)); t += 1
        rows.append(Op(type="ok", f="add", value=1, process=v % 5,
                       time=t)); t += 1
    rows.append(Op(type="invoke", f="read", value=None, process=7, time=t))
    rows.append(Op(type="ok", f="read", value=5000, process=7, time=t + 1))
    t0 = _t.time()
    rc = counter().check({}, History.of(rows))
    print(f"# secondary: 10k-op counter fold: {rc['valid']} in "
          f"{_t.time()-t0:.3f}s", file=sys.stderr)

    rows = []
    t = 0
    for v in range(5000):
        rows.append(Op(type="invoke", f="add", value=v, process=v % 5,
                       time=t)); t += 1
        rows.append(Op(type="ok", f="add", value=v, process=v % 5,
                       time=t)); t += 1
    rows.append(Op(type="invoke", f="read", value=None, process=7, time=t))
    rows.append(Op(type="ok", f="read", value=sorted(range(5000)),
                   process=7, time=t + 1))
    t0 = _t.time()
    rs2 = set_checker().check({}, History.of(rows))
    print(f"# secondary: 10k-op set fold: {rs2['valid']} in "
          f"{_t.time()-t0:.3f}s", file=sys.stderr)

    rows = []
    t = 0
    for v in range(5000):
        for f in ("enqueue", "dequeue"):
            rows.append(Op(type="invoke", f=f, value=v,
                           process=0 if f == "enqueue" else 1, time=t))
            rows.append(Op(type="ok", f=f, value=v,
                           process=0 if f == "enqueue" else 1, time=t + 1))
            t += 2
    t0 = _t.time()
    rt = total_queue().check({}, History.of(rows))
    print(f"# secondary: 10k-op total-queue fold: {rt['valid']} in "
          f"{_t.time()-t0:.3f}s", file=sys.stderr)

    # host-side native engine (C++ WGL twin): the same verdicts with
    # zero compile cost — the framework's single-history CPU path
    from jepsen_tpu.checker.native import (
        available, check_history_native, check_keyed_native)
    if available():
        h10 = simulate_register_history(N_OPS, n_procs=N_PROCS, n_vals=16,
                                        seed=42, crash_p=0.002)
        t0 = _t.time()
        rn = check_history_native(h10, CASRegister())
        print(f"# secondary: native engine 10k-op: {rn['valid']} in "
              f"{_t.time()-t0:.3f}s", file=sys.stderr)
        t0 = _t.time()
        rn = check_history_native(h, CASRegister())
        print(f"# secondary: native engine 100k-op: {rn['valid']} in "
              f"{_t.time()-t0:.3f}s", file=sys.stderr)
        t0 = _t.time()
        rk = check_keyed_native(keyed, CASRegister())
        nk = sum(1 for x in rk["results"].values() if x["valid"] is True)
        print(f"# secondary: native engine 50 keys x 200 ops: {nk}/50 "
              f"valid in {_t.time()-t0:.3f}s", file=sys.stderr)

        # stretch: 100x the north star — 1M ops through the native
        # engine (pack + search; the reference's checker "can take
        # hours" at 1/100th of this)
        h1m = simulate_register_history(1_000_000, n_procs=N_PROCS,
                                        n_vals=16, seed=6,
                                        crash_p=0.0001)
        t0 = _t.time()
        rn = check_history_native(h1m, CASRegister())
        print(f"# secondary: native engine 1M-op: {rn['valid']} in "
              f"{_t.time()-t0:.2f}s", file=sys.stderr)


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------




def _relay(stderr: str) -> str:
    """Child stderr tail, with pathological lines dropped first: one LLVM
    cpu-feature warning can be >6000 chars and would evict every real
    measurement line from the recorded tail."""
    keep = [ln for ln in (stderr or "").splitlines() if len(ln) < 1500]
    return "\n".join(keep)[-12000:]

def _run_child(platform: str, timeout: float, skip_secondary: bool = False):
    """Run one measurement child. Returns (record | None, note)."""
    env = dict(os.environ)
    env["JEPSEN_BENCH_CHILD"] = platform
    # The orchestrator already sandboxes children behind its own timeout;
    # the library-level accelerator watchdog probing AGAIN inside the
    # child would double a minutes-long healthy-but-cold TPU init.
    env["JEPSEN_ACCEL_OK"] = "1"
    if platform != "cpu":
        # Soft deadline 45 s ahead of the hard kill: lets the child finish
        # the secondary in flight and exit cleanly, releasing the device
        # lease (a SIGKILL mid-TPU-use can wedge the chip for the next
        # child's init — observed: 10+ min of hung init). CPU children get
        # no deadline: nothing to wedge, and the kill-and-salvage path
        # preserves their stderr tail, so they measure right up to the
        # hard kill. Floored so a near-exhausted budget still yields a
        # moment for the headline before the clean exit.
        env["JEPSEN_BENCH_CHILD_DEADLINE"] = str(
            time.time() + max(10.0, timeout - 45.0))
    if skip_secondary:
        env["JEPSEN_BENCH_SKIP_SECONDARY"] = "1"
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    print(f"# bench: trying platform={platform} (timeout {timeout:.0f}s)",
          file=sys.stderr)
    try:
        pr = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired as e:
        def _s(x):
            if isinstance(x, bytes):
                return x.decode(errors="replace")
            return x or ""
        print(_relay(_s(e.stderr)), file=sys.stderr)
        # the headline prints before the secondaries: a child killed mid-
        # secondary still yields its number
        for line in reversed(_s(e.stdout).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return (json.loads(line),
                            f"{platform}: ok (timeout during secondaries)")
                except json.JSONDecodeError:
                    continue
        # "wedged" vs "slow": a child that never even printed its device
        # line hung in backend INIT — a retry will hang identically, so
        # the orchestrator should fall through to CPU with the budget
        # that remains instead of burning it on a second silent hang.
        if "# device:" not in _s(e.stderr):
            return None, f"{platform}: wedged (no device after " \
                         f"{timeout:.0f}s)"
        return None, f"{platform}: timeout after {timeout:.0f}s"
    except Exception as e:  # noqa: BLE001
        return None, f"{platform}: spawn failed: {e!r}"
    sys.stderr.write(_relay(pr.stderr) + "\n" if pr.stderr else "")
    for line in reversed((pr.stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), f"{platform}: ok"
            except json.JSONDecodeError:
                continue
    return None, f"{platform}: no JSON line (rc={pr.returncode})"


def main() -> int:
    deadline = time.time() + BUDGET_S
    notes = []

    def on_term(signum, frame):  # driver timeout: still leave a record
        emit(None, 0, error=f"killed by signal {signum}; " + "; ".join(notes))
        sys.exit(1)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    # Cheap init probe before committing a full child budget to the TPU:
    # a wedged chip lease hangs backend init for 10+ minutes, so a full
    # attempt would burn its whole timeout in init. Reuses the library
    # watchdog (jepsen_tpu.accel): disposable child, returncode check,
    # output sentinel, shared timeout default; importing it does NOT
    # initialize a backend in this process. Skipped when the operator
    # vouches for the accelerator (JEPSEN_ACCEL_OK — accel's trust path
    # would answer with the *configured* platform, which reads as "cpu"
    # on hosts that pin nothing, wrongly skipping healthy TPU attempts).
    tpu_ok = True
    if (not os.environ.get("JEPSEN_BENCH_SKIP_PROBE")
            and not os.environ.get("JEPSEN_ACCEL_OK")):
        from jepsen_tpu.accel import PROBE_TIMEOUT_S, probe_default_backend
        remaining = deadline - time.time()
        probe_t = min(PROBE_TIMEOUT_S, remaining - 90.0)
        # The probe certifies health but does not warm the child — a
        # healthy TPU attempt repeats the init. On the default path,
        # probe only when the budget can absorb both (else let the first
        # attempt discover the backend state itself, as before). An
        # explicit operator cap (JEPSEN_ACCEL_PROBE_TIMEOUT) is intent:
        # honored without the double-init reserve.
        explicit = "JEPSEN_ACCEL_PROBE_TIMEOUT" in os.environ
        need = probe_t + (0.0 if explicit else 240.0)
        if probe_t >= min(30.0, PROBE_TIMEOUT_S) and remaining - 90 >= need:
            t0 = time.time()
            plat = probe_default_backend(timeout=probe_t)
            tpu_ok = plat not in (None, "cpu")
            note = (f"probe: {plat or 'no accelerator'} "
                    f"({time.time() - t0:.0f}s)")
            print(f"# bench: {note}", file=sys.stderr)
            notes.append(note)

    # TPU attempts (sandboxed: a hung plugin init gets killed, not us),
    # with one backoff retry — transient UNAVAILABLE at init is common.
    for attempt in range(2 if tpu_ok else 0):
        remaining = deadline - time.time()
        if remaining < 120:
            notes.append("tpu: out of budget")
            break
        rec, note = _run_child("tpu", min(480.0, remaining - 90))
        notes.append(note)
        if rec is None and "wedged" in note:
            break  # hard init hang: a retry would hang identically
        if rec is not None and rec.get("value") is not None:
            extras = {k: rec[k] for k in ("cold_s", "cold_vs_baseline",
                                          "compile_s", "execute_s",
                                          "compile", "transfer_mb",
                                          "search")
                      if k in rec}
            # Second cold child: same measurement in a FRESH process —
            # its cold_s shows whether the persistent compilation cache
            # actually eliminates the compile across processes.
            remaining = deadline - time.time()
            if remaining > 180:
                rec2, note2 = _run_child(
                    "tpu", min(300.0, remaining - 60), skip_secondary=True)
                notes.append(note2)
                if rec2 is not None and rec2.get("cold_s") is not None:
                    extras["cached_cold_s"] = rec2["cold_s"]
            # CPU comparison line: the same measurement on the host
            # backend, so a TPU run still records both platforms.
            remaining = deadline - time.time()
            if remaining > 150:
                rec3, note3 = _run_child(
                    "cpu", min(240.0, remaining - 60), skip_secondary=True)
                notes.append(note3)
                if rec3 is not None and rec3.get("value") is not None:
                    extras["cpu_warm_s"] = rec3["value"]
                    extras["cpu_cold_s"] = rec3.get("cold_s")
            emit(rec["value"], rec["vs_baseline"],
                 platform=rec.get("platform", "tpu"), **extras)
            return 0
        if attempt == 0:
            time.sleep(5)

    # CPU fallback: the same measurement on the host backend — slower but
    # always records a real number.
    remaining = deadline - time.time()
    if remaining > 60:
        rec, note = _run_child("cpu", remaining - 30)
        notes.append(note)
        if rec is not None and rec.get("value") is not None:
            extras = {k: rec[k] for k in ("cold_s", "cold_vs_baseline",
                                          "compile_s", "execute_s",
                                          "compile", "transfer_mb",
                                          "search")
                      if k in rec}
            emit(rec["value"], rec["vs_baseline"], platform="cpu",
                 note="tpu unavailable; cpu-backend fallback", **extras)
            return 0

    emit(None, 0, error="; ".join(notes))
    return 1


if __name__ == "__main__":
    plat = os.environ.get("JEPSEN_BENCH_CHILD")
    if plat:
        sys.exit(child_main(plat))
    sys.exit(main())
